"""Time-major RNN language model (reference
example/rnn-time-major/rnn_cell_demo.py + bucket_io.py time_major=True).

Exercises the time-major layout path end to end:
  * an iterator whose ``provide_data`` declares layout ``"TN"`` — the
    batch axis is 1, so ``DataParallelExecutorGroup`` slices/pads along
    ``major_axis`` 1 (reference ``executor_group.py:16-66``
    layout-aware slicing, ``io.py:23-80`` LayoutMapper);
  * the fused ``RNN`` symbol consuming (T, N, F) directly — on TPU the
    time axis is the ``lax.scan`` carry dimension, so time-major is the
    layout the compiled step already wants (the reference measured
    time-major 1.5-2x faster than batch-major; here it avoids any
    transpose between embedding and scan);
  * ``SoftmaxOutput(preserve_shape=True)`` with (T, N) labels;
  * initial RNN states fed as data from the iterator (reference
    ``init_states`` convention) rather than learned parameters.

Task (zero-egress stand-in for PTB): predict the next token of
deterministic arithmetic sequences x[t+1] = (x[t] + step) % V with the
step identifying each sequence. Perplexity must fall well below the
uniform-guess baseline V after two epochs.
"""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx
from mxnet_tpu.io import DataBatch, DataDesc, DataIter

logging.basicConfig(level=logging.INFO)

VOCAB = 8
SEQ_LEN = 12
BATCH = 16
HIDDEN = 32
LAYERS = 1


class TimeMajorIter(DataIter):
    """Yields (T, N) token batches plus zero initial states (reference
    BucketSentenceIter(time_major=True) + init_states)."""

    def __init__(self, num_batches, seed):
        super().__init__()
        self.batch_size = BATCH
        rng = np.random.RandomState(seed)
        self._batches = []
        for _ in range(num_batches):
            start = rng.randint(0, VOCAB, size=BATCH)
            step = rng.randint(1, VOCAB, size=BATCH)
            t = np.arange(SEQ_LEN + 1)[:, None]
            seq = (start[None, :] + t * step[None, :]) % VOCAB  # (T+1, N)
            self._batches.append((seq[:-1].astype(np.float32),
                                  seq[1:].astype(np.float32)))
        self._i = -1

    @property
    def provide_data(self):
        shapes = [
            DataDesc("data", (SEQ_LEN, BATCH), layout="TN"),
            DataDesc("rnn_state", (LAYERS, BATCH, HIDDEN), layout="LNC"),
            DataDesc("rnn_state_cell", (LAYERS, BATCH, HIDDEN),
                     layout="LNC"),
        ]
        return shapes

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (SEQ_LEN, BATCH), layout="TN")]

    def reset(self):
        self._i = -1

    def iter_next(self):
        self._i += 1
        return self._i < len(self._batches)

    def getdata(self):
        data, _ = self._batches[self._i]
        zeros = mx.nd.zeros((LAYERS, BATCH, HIDDEN))
        return [mx.nd.array(data), zeros,
                mx.nd.zeros((LAYERS, BATCH, HIDDEN))]

    def getlabel(self):
        return [mx.nd.array(self._batches[self._i][1])]


def sym_gen():
    data = mx.sym.Variable("data")              # (T, N) token ids
    label = mx.sym.Variable("softmax_label")    # (T, N)
    embed = mx.sym.Embedding(data=data, input_dim=VOCAB,
                             output_dim=HIDDEN, name="embed")  # (T, N, H)
    rnn = mx.sym.RNN(data=embed,
                     state=mx.sym.Variable("rnn_state"),
                     state_cell=mx.sym.Variable("rnn_state_cell"),
                     parameters=mx.sym.Variable("rnn_parameters"),
                     state_size=HIDDEN, num_layers=LAYERS,
                     mode="lstm", name="rnn")   # (T, N, H)
    hidden = mx.sym.Reshape(data=rnn, shape=(-1, HIDDEN))
    pred = mx.sym.FullyConnected(data=hidden, num_hidden=VOCAB,
                                 name="pred")
    pred_tm = mx.sym.Reshape(data=pred, shape=(SEQ_LEN, -1, VOCAB))
    sm = mx.sym.SoftmaxOutput(data=pred_tm, label=label,
                              preserve_shape=True, name="softmax")
    return sm


def perplexity(label, pred):
    label = label.reshape(-1).astype(int)
    pred = pred.reshape(-1, pred.shape[-1])
    probs = np.maximum(pred[np.arange(len(label)), label], 1e-10)
    return float(np.exp(-np.log(probs).mean()))


def main():
    train = TimeMajorIter(num_batches=30, seed=0)
    val = TimeMajorIter(num_batches=4, seed=1)

    mod = mx.mod.Module(sym_gen(), context=mx.cpu(),
                        data_names=["data", "rnn_state", "rnn_state_cell"],
                        label_names=["softmax_label"])
    metric = mx.metric.np_metric(perplexity, name="perplexity")
    mod.fit(train, eval_data=val, num_epoch=4, eval_metric=metric,
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.34),
            optimizer="adam", optimizer_params={"learning_rate": 0.01})

    score = dict(mod.score(val, mx.metric.np_metric(perplexity,
                                                    name="perplexity")))
    ppl = next(iter(score.values()))
    logging.info("validation perplexity %.3f (uniform baseline %d)",
                 ppl, VOCAB)
    assert ppl < 2.0, score
    # confirm the layout really is time-major through the module path
    assert DataDesc.get_batch_axis(train.provide_data[0].layout) == 1
    print("rnn time major OK")


if __name__ == "__main__":
    main()
