#!/usr/bin/env python
"""Noise-contrastive estimation (reference example/nce-loss): train a
large-vocabulary next-token scorer without a full softmax — score the
true class against k sampled noise classes with logistic loss, built
from Embedding + batch_dot like the reference's nce.py.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

VOCAB = 200
EMBED = 24
K = 8  # noise samples per example


def build_net():
    data = mx.sym.Variable("data")            # (N,) context token
    cand = mx.sym.Variable("cand")            # (N, 1+K) true + noise ids
    in_vec = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                              name="in_embed")           # (N, E)
    out_vec = mx.sym.Embedding(cand, input_dim=VOCAB, output_dim=EMBED,
                               name="out_embed")         # (N, 1+K, E)
    q = mx.sym.Reshape(in_vec, shape=(-1, EMBED, 1))     # (N, E, 1)
    logits = mx.sym.batch_dot(out_vec, q)                # (N, 1+K, 1)
    logits = mx.sym.Reshape(logits, shape=(-1, 1 + K))
    return mx.sym.LogisticRegressionOutput(
        data=logits, label=mx.sym.Variable("label"), name="nce")


def main(seed=0, epochs=12, batch=64):
    rng = np.random.RandomState(seed)
    # deterministic bigram structure: next = (ctx * 7 + 3) % VOCAB
    n = 1024
    ctx_tok = rng.randint(0, VOCAB, n)
    true_next = (ctx_tok * 7 + 3) % VOCAB
    net = build_net()
    exe = net.simple_bind(mx.cpu(), data=(batch,), cand=(batch, 1 + K),
                          label=(batch, 1 + K))
    init = mx.init.Uniform(0.1)
    for name, arr in exe.arg_dict.items():
        if name.endswith("weight"):
            init(name, arr)
    updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=1e-2))
    skip = {"data", "cand", "label"}
    label = np.zeros((batch, 1 + K), np.float32)
    label[:, 0] = 1.0

    for epoch in range(epochs):
        for i in range(0, n - batch + 1, batch):
            c = ctx_tok[i:i + batch]
            t = true_next[i:i + batch]
            noise = rng.randint(0, VOCAB, (batch, K))
            cand = np.concatenate([t[:, None], noise], axis=1)
            exe.arg_dict["data"][:] = c.astype(np.float32)
            exe.arg_dict["cand"][:] = cand.astype(np.float32)
            exe.arg_dict["label"][:] = label
            exe.forward(is_train=True)
            exe.backward()
            for j, name in enumerate(net.list_arguments()):
                if name in skip:
                    continue
                updater(j, exe.grad_dict[name], exe.arg_dict[name])

    # evaluation: full-vocabulary argmax using the learned embeddings
    in_w = exe.arg_dict["in_embed_weight"].asnumpy()
    out_w = exe.arg_dict["out_embed_weight"].asnumpy()
    test_ctx = rng.randint(0, VOCAB, 256)
    scores = in_w[test_ctx] @ out_w.T                    # (256, VOCAB)
    pred = scores.argmax(axis=1)
    acc = (pred == (test_ctx * 7 + 3) % VOCAB).mean()
    print("full-softmax top-1 from NCE-trained embeddings: %.3f" % acc)
    assert acc > 0.6, acc
    print("NCE OK")


if __name__ == "__main__":
    main()
