#!/usr/bin/env python
"""Stacked autoencoder (reference example/autoencoder): encoder/decoder
MLP trained with LinearRegressionOutput reconstructing its input, then
the bottleneck reused as features for a classifier.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def build_autoencoder(n_hidden=8):
    data = mx.sym.Variable("data")
    enc = mx.sym.FullyConnected(data, num_hidden=32, name="enc1")
    enc = mx.sym.Activation(enc, act_type="relu")
    code = mx.sym.FullyConnected(enc, num_hidden=n_hidden, name="code")
    dec = mx.sym.Activation(code, act_type="relu")
    dec = mx.sym.FullyConnected(dec, num_hidden=64, name="dec1")
    recon = mx.sym.LinearRegressionOutput(
        data=dec, label=mx.sym.Variable("recon_label"), name="recon")
    return recon


def main(seed=0):
    rng = np.random.RandomState(seed)
    # data living on a low-dim manifold: 64-d from 4 latent factors
    n = 512
    latent = rng.randn(n, 4)
    mix = rng.randn(4, 64)
    X = np.tanh(latent @ mix).astype(np.float32)

    ae = build_autoencoder()
    it = mx.io.NDArrayIter({"data": X}, {"recon_label": X}, batch_size=64,
                           shuffle=True)
    exe = ae.simple_bind(mx.cpu(), data=(64, 64), recon_label=(64, 64))
    init = mx.init.Xavier()
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "recon_label"):
            init(name, arr)
    opt = mx.optimizer.create("adam", learning_rate=1e-2)
    updater = mx.optimizer.get_updater(opt)

    def mse():
        it.reset()
        errs = []
        for batch in it:
            exe.arg_dict["data"][:] = batch.data[0]
            exe.arg_dict["recon_label"][:] = batch.label[0]
            out = exe.forward()[0].asnumpy()
            errs.append(((out - batch.label[0].asnumpy()) ** 2).mean())
        return float(np.mean(errs))

    before = mse()
    for epoch in range(15):
        it.reset()
        for batch in it:
            exe.arg_dict["data"][:] = batch.data[0]
            exe.arg_dict["recon_label"][:] = batch.label[0]
            exe.forward(is_train=True)
            exe.backward()
            for i, name in enumerate(ae.list_arguments()):
                if name in ("data", "recon_label"):
                    continue
                updater(i, exe.grad_dict[name], exe.arg_dict[name])
    after = mse()
    print("reconstruction mse: %.4f -> %.4f" % (before, after))
    assert after < before * 0.3, (before, after)
    print("autoencoder OK")


if __name__ == "__main__":
    main()
