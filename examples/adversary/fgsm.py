#!/usr/bin/env python
"""Adversarial examples via FGSM (reference example/adversary).

Trains a small MLP classifier, then computes the fast-gradient-sign
perturbation from the executor's *data* gradient (``grad_req`` on the
input — the same executor mechanics the reference notebook used) and
shows accuracy collapsing on the perturbed batch.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def build_net():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main(epsilon=1.0, seed=0):
    rng = np.random.RandomState(seed)
    # 4 gaussian blobs in 16-d
    n, d = 512, 16
    y = rng.randint(0, 4, n).astype(np.float32)
    centers = rng.randn(4, d) * 1.5
    X = (centers[y.astype(int)] + rng.randn(n, d) * 0.5).astype(np.float32)

    net = build_net()
    model = mx.model.FeedForward.create(
        net, X=mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True),
        num_epoch=10, learning_rate=0.1, ctx=mx.cpu())
    clean_acc = (model.predict(mx.io.NDArrayIter(X, y, batch_size=64))
                 .argmax(axis=1) == y).mean()

    # executor with a gradient on the DATA input
    exe = net.simple_bind(mx.cpu(), grad_req={"data": "write"},
                          data=(n, d))
    for k, v in model.arg_params.items():
        exe.arg_dict[k][:] = v
    exe.arg_dict["data"][:] = X
    exe.arg_dict["softmax_label"][:] = y
    exe.forward(is_train=True)
    exe.backward()
    grad_sign = np.sign(exe.grad_dict["data"].asnumpy())
    X_adv = (X + epsilon * grad_sign).astype(np.float32)

    adv_acc = (model.predict(mx.io.NDArrayIter(X_adv, y, batch_size=64))
               .argmax(axis=1) == y).mean()
    print("clean accuracy: %.3f  adversarial accuracy: %.3f (eps=%.2f)"
          % (clean_acc, adv_acc, epsilon))
    assert clean_acc > 0.9, clean_acc
    assert adv_acc < clean_acc - 0.1, (clean_acc, adv_acc)
    print("FGSM OK")


if __name__ == "__main__":
    main()
