#!/usr/bin/env python
"""Training memory cost (reference example/memcost + memonger):
quantify what ``MXNET_BACKWARD_DO_MIRROR`` buys on a deep MLP.

The mirror flag routes graph evaluation through segmented
rematerialization (``make_graph_eval(remat=True)``): the topo order is
split into ~sqrt(N) ``jax.checkpoint`` segments, so the backward pass
stores only segment-boundary activations and recomputes inside each
segment — the reference memonger's sqrt schedule. The measured quantity
is the byte size of the residuals the vjp must hold between forward and
backward (the activation memory remat exists to shrink); the price is
one extra forward's worth of FLOPs, reported via XLA's cost analysis.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx
from mxnet_tpu.executor import make_graph_eval

DEPTH = 24
WIDTH = 256
BATCH = 256


def build():
    net = mx.sym.Variable("data")
    for i in range(DEPTH):
        net = mx.sym.FullyConnected(net, num_hidden=WIDTH,
                                    name="fc%d" % i)
        net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="cls")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def measure(remat: bool):
    """(residual bytes held between fwd and bwd, train-step flops)."""
    net = build()
    ev, _ = make_graph_eval(net, remat=remat)
    arg_shapes, _, _ = net.infer_shape(data=(BATCH, WIDTH))
    rng = np.random.RandomState(0)
    args = [rng.randn(*s).astype(np.float32) * 0.05 for s in arg_shapes]
    key = jax.random.PRNGKey(0)

    def f(args):
        outs, _aux = ev(args, [], key, True)
        return outs[0]

    _, vjp = jax.vjp(f, args)
    res_bytes = sum(l.nbytes for l in jax.tree_util.tree_leaves(vjp)
                    if hasattr(l, "nbytes"))
    # the vjp also holds the args themselves (params + data are matmul
    # backward operands whether or not remat is on) — a constant floor
    # that is not activation memory; subtract it so the ratio measures
    # what remat can actually shrink
    arg_bytes = sum(a.nbytes for a in args)
    res_bytes = max(0, res_bytes - arg_bytes)

    # recompute cost: count matmuls in the emitted (pre-optimization)
    # backward program — remat re-runs each segment's forward inside the
    # backward, guarded by optimization_barrier so the compiler must
    # honor it (a backend MAY still trade it back; CPU XLA does)
    txt = jax.jit(jax.grad(lambda a: f(a).sum())).lower(args).as_text()
    dots = txt.count("stablehlo.dot")
    barriers = txt.count("optimization_barrier")
    return res_bytes, dots, barriers


def main():
    plain_bytes, plain_dots, _ = measure(False)
    remat_bytes, remat_dots, barriers = measure(True)
    mem_ratio = remat_bytes / plain_bytes
    dot_ratio = remat_dots / plain_dots
    print("%d-layer MLP, batch %d: stored activations %.1f -> %.1f MiB "
          "(%.2fx); emitted matmuls %d -> %d (%.2fx recompute), "
          "%d segment barriers"
          % (DEPTH, BATCH, plain_bytes / 2**20, remat_bytes / 2**20,
             mem_ratio, plain_dots, remat_dots, dot_ratio, barriers))
    # sqrt-schedule remat: stored activations shrink by a lot, at the
    # price of at most one extra forward of recompute
    assert mem_ratio < 0.3, mem_ratio
    assert plain_dots < remat_dots <= 2 * plain_dots, (plain_dots,
                                                       remat_dots)
    assert barriers > 0
    print("memcost OK")


if __name__ == "__main__":
    main()
