"""Speech acoustic-model demo (reference example/speech-demo/):
frame-level classification with an explicitly unrolled projection LSTM
(lstm_proj.py) trained by the speechSGD optimizer (speechSGD.py).

What this family uniquely exercises:
  * LSTMP — LSTM with a recurrent PROJECTION layer: the hidden state
    fed back into the recurrence is a lower-dimensional linear
    projection of the cell output (Sak et al.; reference
    ``lstm_proj.py:16-58``), plus peephole connections implemented as
    broadcast_mul with (1, H)-shaped bias variables;
  * an unrolled per-timestep symbol graph (node-per-timestep, shared
    weight variables — the reference's pre-scan RNN style) rather than
    the fused RNN op;
  * a custom optimizer registered from user code: speechSGD's momentum
    rule ``mom = momentum*mom - lr*(1-momentum)*(grad + wd*w)``
    (reference ``speechSGD.py:76-110``), exercising the optimizer
    registry extension path.

Zero-egress stand-in for Kaldi features: synthetic utterances whose
frame class depends on a sliding window of the input, so temporal
context (the LSTM memory) is required to beat a frame-wise classifier.
"""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

logging.basicConfig(level=logging.INFO)

SEQ_LEN = 10
NFEAT = 6
NHID = 24
NPROJ = 12
NCLASS = 3
BATCH = 16


@mx.optimizer.register
class speechSGD(mx.optimizer.Optimizer):
    """The reference's speech-recipe momentum rule (speechSGD.py):
    the gradient term is scaled by (1 - momentum)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return mx.nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = mx.nd.clip(g, -self.clip_gradient, self.clip_gradient)
        if state is not None:
            state[:] = self.momentum * state \
                - lr * (1.0 - self.momentum) * (g + wd * weight)
            weight[:] = weight + state
        else:
            weight[:] = weight - lr * (g + wd * weight)


def lstmp_cell(num_hidden, num_proj, indata, prev_c, prev_h, params, t):
    """One unrolled LSTMP step (reference lstm_proj.py lstm()):
    peephole terms via broadcast_mul of (1, H) biases with the cell."""
    i2h = mx.sym.FullyConnected(data=indata, weight=params["i2h_weight"],
                                bias=params["i2h_bias"],
                                num_hidden=num_hidden * 4,
                                name="t%d_i2h" % t)
    h2h = mx.sym.FullyConnected(data=prev_h, weight=params["h2h_weight"],
                                no_bias=True, num_hidden=num_hidden * 4,
                                name="t%d_h2h" % t)
    gates = mx.sym.SliceChannel(i2h + h2h, num_outputs=4,
                                name="t%d_slice" % t)
    in_gate = mx.sym.Activation(
        mx.sym.broadcast_mul(params["c2i_bias"], prev_c) + gates[0],
        act_type="sigmoid")
    in_transform = mx.sym.Activation(gates[1], act_type="tanh")
    forget_gate = mx.sym.Activation(
        mx.sym.broadcast_mul(params["c2f_bias"], prev_c) + gates[2],
        act_type="sigmoid")
    next_c = forget_gate * prev_c + in_gate * in_transform
    out_gate = mx.sym.Activation(
        mx.sym.broadcast_mul(params["c2o_bias"], next_c) + gates[3],
        act_type="sigmoid")
    next_h = out_gate * mx.sym.Activation(next_c, act_type="tanh")
    # the projection: what recurs is W_p * h, dim num_proj < num_hidden
    proj_h = mx.sym.FullyConnected(data=next_h,
                                   weight=params["ph2h_weight"],
                                   no_bias=True, num_hidden=num_proj,
                                   name="t%d_ph2h" % t)
    return next_c, proj_h


def lstmp_unroll(seq_len, num_hidden, num_proj, num_label):
    params = {
        "i2h_weight": mx.sym.Variable("l0_i2h_weight"),
        "i2h_bias": mx.sym.Variable("l0_i2h_bias"),
        "h2h_weight": mx.sym.Variable("l0_h2h_weight"),
        "ph2h_weight": mx.sym.Variable("l0_ph2h_weight"),
        "c2i_bias": mx.sym.Variable("l0_c2i_bias", shape=(1, num_hidden)),
        "c2f_bias": mx.sym.Variable("l0_c2f_bias", shape=(1, num_hidden)),
        "c2o_bias": mx.sym.Variable("l0_c2o_bias", shape=(1, num_hidden)),
    }
    cls_weight = mx.sym.Variable("cls_weight")
    cls_bias = mx.sym.Variable("cls_bias")
    data = mx.sym.Variable("data")          # (batch, T, feat)
    label = mx.sym.Variable("softmax_label")  # (batch, T)
    frames = mx.sym.SliceChannel(data, num_outputs=seq_len, axis=1,
                                 squeeze_axis=True, name="frames")
    c = mx.sym.Variable("init_c")
    h = mx.sym.Variable("init_h")
    outs = []
    for t in range(seq_len):
        c, h = lstmp_cell(num_hidden, num_proj, frames[t], c, h, params, t)
        fc = mx.sym.FullyConnected(data=h, weight=cls_weight,
                                   bias=cls_bias, num_hidden=num_label,
                                   name="t%d_cls" % t)
        outs.append(fc)
    pred = mx.sym.Concat(*[mx.sym.Reshape(o, shape=(-1, 1, num_label))
                           for o in outs], dim=1)   # (batch, T, nclass)
    return mx.sym.SoftmaxOutput(data=pred, label=label,
                                preserve_shape=True, name="softmax")


def make_data(rng, n):
    """Class of frame t = sign pattern of feature-sums over a 3-frame
    window: needs memory, a frame-wise classifier caps at ~chance."""
    X = rng.randn(n, SEQ_LEN, NFEAT).astype(np.float32)
    s = X.sum(axis=2)
    ctx = np.stack([np.roll(s, 1, axis=1), s,
                    np.roll(s, 2, axis=1)], axis=0)
    y = ((ctx[0] > 0).astype(int) + (ctx[2] > 0).astype(int))
    y[:, :2] = 0      # frames without full context get class 0
    return X, y.astype(np.float32)


def main():
    rng = np.random.RandomState(3)
    X, y = make_data(rng, 480)
    Xv, yv = make_data(rng, 96)

    net = lstmp_unroll(SEQ_LEN, NHID, NPROJ, NCLASS)

    class UttIter(mx.io.DataIter):
        def __init__(self, X, y):
            super().__init__()
            self.X, self.y = X, y
            self.batch_size = BATCH
            self.cursor = -BATCH

        @property
        def provide_data(self):
            return [mx.io.DataDesc("data", (BATCH, SEQ_LEN, NFEAT)),
                    mx.io.DataDesc("init_c", (BATCH, NHID)),
                    mx.io.DataDesc("init_h", (BATCH, NPROJ))]

        @property
        def provide_label(self):
            return [mx.io.DataDesc("softmax_label", (BATCH, SEQ_LEN))]

        def reset(self):
            self.cursor = -BATCH

        def iter_next(self):
            self.cursor += BATCH
            return self.cursor + BATCH <= len(self.X)

        def getdata(self):
            sl = slice(self.cursor, self.cursor + BATCH)
            return [mx.nd.array(self.X[sl]),
                    mx.nd.zeros((BATCH, NHID)),
                    mx.nd.zeros((BATCH, NPROJ))]

        def getlabel(self):
            sl = slice(self.cursor, self.cursor + BATCH)
            return [mx.nd.array(self.y[sl])]

    def frame_acc(label, pred):
        lab = label.reshape(-1).astype(int)
        p = pred.reshape(-1, NCLASS)
        return float((p.argmax(axis=1) == lab).mean())

    mod = mx.mod.Module(net,
                        data_names=["data", "init_c", "init_h"],
                        label_names=["softmax_label"], context=mx.cpu())
    mod.fit(UttIter(X, y), num_epoch=8,
            eval_metric=mx.metric.np_metric(frame_acc, name="frame_acc"),
            initializer=mx.initializer.Xavier(magnitude=2.0),
            optimizer="speechsgd",
            optimizer_params={"learning_rate": 0.06, "momentum": 0.9})

    score = dict(mod.score(UttIter(Xv, yv),
                           mx.metric.np_metric(frame_acc,
                                               name="frame_acc")))
    acc = next(iter(score.values()))
    logging.info("frame accuracy %.3f (chance ~0.4)", acc)
    assert acc > 0.8, score
    print("speech demo OK")


if __name__ == "__main__":
    main()
