#!/usr/bin/env python
"""Toy CTC sequence recognition (reference example/warpctc/toy_ctc.py):
random 4-digit strings rendered as 80-frame one-hot-ish features (each
digit spans 20 noisy frames), recognized by an RNN + WarpCTC.

Demonstrates the plugin-parity surface: sym.WarpCTC consumes (T*B, A)
time-major activations and 0-padded labels (blank=0), exactly like the
reference's warp-ctc operator; greedy CTC decoding collapses repeats and
strips blanks."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym

SEQ_LEN = 80          # frames
DIGIT_SPAN = 20       # frames per digit
NUM_DIGIT = 4         # digits per sequence
NUM_CLASSES = 11      # blank + 10 digits (labels are digit+1)
FEAT = 10
BATCH = 32
NUM_HIDDEN = 64


def gen_batch(rng, batch):
    """Features (T, B, FEAT) and labels (B, NUM_DIGIT) with blank=0
    convention (digit d -> class d+1)."""
    data = np.zeros((SEQ_LEN, batch, FEAT), dtype=np.float32)
    labels = np.zeros((batch, NUM_DIGIT), dtype=np.float32)
    for b in range(batch):
        digits = rng.randint(0, 10, NUM_DIGIT)
        labels[b] = digits + 1
        for i, d in enumerate(digits):
            data[i * DIGIT_SPAN:(i + 1) * DIGIT_SPAN, b, d] = 1.0
    data += rng.randn(*data.shape).astype(np.float32) * 0.15
    return data, labels


def build_net():
    data = sym.Variable("data")                      # (T, B, FEAT)
    # bidirectional: digit boundaries need right context for CTC to
    # place blanks (the unidirectional variant plateaus at nll ~3)
    rnn = sym.RNN(data=data, state_size=NUM_HIDDEN, num_layers=1,
                  mode="gru", bidirectional=True, name="gru")
    body = sym.Reshape(rnn, shape=(-1, 2 * NUM_HIDDEN))  # (T*B, 2H)
    pred = sym.FullyConnected(data=body, num_hidden=NUM_CLASSES,
                              name="pred")
    return sym.WarpCTC(data=pred, label=sym.Variable("label"),
                       input_length=SEQ_LEN, label_length=NUM_DIGIT)


def greedy_decode(probs_tb):
    """(T, A) -> collapse repeats, strip blanks (class 0)."""
    best = probs_tb.argmax(axis=1)
    out, prev = [], -1
    for c in best:
        if c != prev and c != 0:
            out.append(int(c) - 1)
        prev = c
    return out


def main(num_iters=1600, lr=0.005, seed=0):
    rng = np.random.RandomState(seed)
    net = build_net()
    arg_shapes, _, aux_shapes = net.infer_shape(
        data=(SEQ_LEN, BATCH, FEAT), label=(BATCH, NUM_DIGIT))
    arg_names = net.list_arguments()
    init = mx.init.Xavier()
    args, grads, req = {}, {}, {}
    for name, shape in zip(arg_names, arg_shapes):
        args[name] = mx.nd.zeros(shape)
        if name in ("data", "label"):
            req[name] = "null"
        else:
            init(name, args[name])
            grads[name] = mx.nd.zeros(shape)
            req[name] = "write"
    ex = net.bind(mx.cpu(), args, args_grad=grads, grad_req=req)

    # CTC + RNN gradients explode without clipping (the reference's
    # lstm_ocr sets clip_gradient); adam + elementwise clip, via the
    # framework's own optimizer registry
    opt = mx.optimizer.create("adam", learning_rate=lr,
                              clip_gradient=1.0, rescale_grad=1.0 / BATCH)
    updater = mx.optimizer.get_updater(opt)
    pnames = sorted(grads)
    for it in range(num_iters):
        data, labels = gen_batch(rng, BATCH)
        args["data"][:] = data
        args["label"][:] = labels
        ex.forward(is_train=True)
        ex.backward()
        for i, name in enumerate(pnames):
            updater(i, grads[name], args[name])
        if (it + 1) % 100 == 0:
            probs = ex.outputs[0].asnumpy().reshape(SEQ_LEN, BATCH, -1)
            hits = sum(
                greedy_decode(probs[:, b]) ==
                [int(v) - 1 for v in labels[b]]
                for b in range(BATCH))
            print("iter %d seq-accuracy %.2f" % (it + 1, hits / BATCH))

    # final evaluation on fresh sequences
    data, labels = gen_batch(rng, BATCH)
    args["data"][:] = data
    args["label"][:] = labels
    ex.forward(is_train=False)
    probs = ex.outputs[0].asnumpy().reshape(SEQ_LEN, BATCH, -1)
    hits = sum(greedy_decode(probs[:, b]) == [int(v) - 1 for v in labels[b]]
               for b in range(BATCH))
    acc = hits / BATCH
    print("Final sequence accuracy: %.2f" % acc)
    return acc


if __name__ == "__main__":
    main()
