#!/usr/bin/env python
"""Frontend-defined operators (reference example/numpy-ops/numpy_softmax.py
and example/python-howto): implement an op in numpy via CustomOp and train
with it.

The CustomOp runs as a host callback inside the compiled graph
(jax.pure_callback + custom_vjp) — the TPU-native form of the reference's
ctypes callback machinery (src/operator/custom-inl.h).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


class NumpySoftmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        lbl = in_data[1].asnumpy().astype(int)
        y = out_data[0].asnumpy().copy()
        y[np.arange(lbl.shape[0]), lbl] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y / lbl.shape[0]))


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


def main():
    rng = np.random.RandomState(0)
    n = 512
    y = rng.randint(0, 4, n).astype(np.float32)
    X = rng.randn(n, 16).astype(np.float32) * 0.3
    X[np.arange(n), (y * 4).astype(int)] += 2.0

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    net = mx.sym.Custom(data=net, label=mx.sym.Variable("softmax_label"),
                        op_type="numpy_softmax", name="softmax")
    mod = mx.mod.Module(net)
    it = mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True,
                           label_name="softmax_label")
    mod.fit(it, num_epoch=8, optimizer_params={"learning_rate": 0.5})
    acc = dict(mod.score(mx.io.NDArrayIter(X, y, batch_size=64,
                                           label_name="softmax_label"),
                         "acc"))
    print("train accuracy with numpy CustomOp softmax: %.3f"
          % acc["accuracy"])
    assert acc["accuracy"] > 0.9


if __name__ == "__main__":
    main()
