"""Kaggle NDSB-1 plankton-classification pipeline (reference
example/kaggle-ndsb1/): the END-TO-END competition workflow —
  1. gen_img_list: walk a class-per-subdirectory image folder, write
     tab-separated .lst files with a stratified train/val split
     (reference gen_img_list.py);
  2. im2rec: pack the lists into recordio (tools/im2rec.py — the
     reference used the same tool);
  3. train: convnet on ImageRecordIter with augmentation
     (reference train_dsb.py over train_model.py);
  4. predict + submission: per-class probability rows indexed by image
     name, header = class names, probabilities summing to 1
     (reference predict_dsb.py + submission_dsb.py gen_sub).

Zero-egress stand-in for the plankton data: generated class-dependent
blob images. Gates: val accuracy and a structurally valid
submission.csv.
"""
import csv
import logging
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

logging.basicConfig(level=logging.INFO)

CLASSES = ["acantharia", "copepod", "detritus", "shrimp"]
IMG = 24
PER_CLASS = 40


def make_image_folder(root, rng):
    """Class-distinguishable grayscale blobs saved as PNGs."""
    from PIL import Image

    yy, xx = np.mgrid[0:IMG, 0:IMG]
    for ci, cls in enumerate(CLASSES):
        d = os.path.join(root, cls)
        os.makedirs(d)
        for i in range(PER_CLASS):
            cx, cy = rng.randint(8, IMG - 8, 2)
            r = 3 + ci * 1.5
            dist = np.sqrt((xx - cx) ** 2 + (yy - cy) ** 2)
            if ci % 2 == 0:
                img = (dist < r) * 200.0
            else:
                img = ((dist < r) & (dist > r - 2)) * 200.0
            img = img + rng.rand(IMG, IMG) * 40.0
            Image.fromarray(img.clip(0, 255).astype(np.uint8)).save(
                os.path.join(d, "img_%s_%d.png" % (cls, i)))


def gen_img_list(image_folder, out_folder, percent_val=0.25, seed=888):
    """reference gen_img_list.py: enumerate class subdirs, write
    train.lst plus a stratified tr.lst/va.lst split."""
    rng = np.random.RandomState(seed)
    rows_by_class = []
    cnt = 0
    for ci, cls in enumerate(sorted(os.listdir(image_folder))):
        rows = []
        for img in sorted(os.listdir(os.path.join(image_folder, cls))):
            rows.append((cnt, ci, os.path.join(cls, img)))
            cnt += 1
        rows_by_class.append(rows)

    def write(path, rows):
        with open(path, "w") as f:
            w = csv.writer(f, delimiter="\t", lineterminator="\n")
            for r in rows:
                w.writerow(r)

    tr, va = [], []
    for rows in rows_by_class:            # stratified split
        rows = list(rows)
        rng.shuffle(rows)
        k = int(len(rows) * percent_val)
        va.extend(rows[:k])
        tr.extend(rows[k:])
    rng.shuffle(tr)
    write(os.path.join(out_folder, "train.lst"),
          [r for rows in rows_by_class for r in rows])
    write(os.path.join(out_folder, "tr.lst"), tr)
    write(os.path.join(out_folder, "va.lst"), va)


def im2rec(lst, image_root, rec):
    repo = os.path.join(os.path.dirname(__file__), "..", "..")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "im2rec.py"),
         "--list", lst, "--encoding", ".png",
         lst.replace(".lst", ""), image_root + "/"],
        capture_output=True, text=True, env=dict(os.environ))
    assert r.returncode == 0, r.stderr[-800:]
    assert os.path.exists(rec), rec


def get_symbol(num_class):
    """Small conv net in the train_dsb.py spirit."""
    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, num_filter=8, kernel=(3, 3), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, num_filter=16, kernel=(3, 3), name="c2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_class, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def gen_sub(predictions, test_lst_path, submission_path):
    """reference submission_dsb.py gen_sub: header of class names,
    one probability row per image, indexed by file name."""
    images = []
    with open(test_lst_path) as f:
        for line in f:
            if line.strip():
                images.append(line.strip().split("\t")[-1].split("/")[-1])
    with open(submission_path, "w") as f:
        w = csv.writer(f)
        w.writerow(["image"] + CLASSES)
        for img, row in zip(images, predictions):
            w.writerow([img] + ["%.6f" % p for p in row])


def main():
    rng = np.random.RandomState(0)
    tmp = tempfile.mkdtemp(prefix="ndsb1_")
    image_root = os.path.join(tmp, "train")
    os.makedirs(image_root)
    make_image_folder(image_root, rng)

    gen_img_list(image_root, tmp)
    im2rec(os.path.join(tmp, "tr.lst"), image_root,
           os.path.join(tmp, "tr.rec"))
    im2rec(os.path.join(tmp, "va.lst"), image_root,
           os.path.join(tmp, "va.rec"))

    train = mx.io.ImageRecordIter(
        path_imgrec=os.path.join(tmp, "tr.rec"), data_shape=(1, IMG, IMG),
        batch_size=20, shuffle=True, rand_mirror=True,
        scale=1.0 / 255, preprocess_threads=2)
    val = mx.io.ImageRecordIter(
        path_imgrec=os.path.join(tmp, "va.rec"), data_shape=(1, IMG, IMG),
        batch_size=20, scale=1.0 / 255)

    mod = mx.mod.Module(get_symbol(len(CLASSES)), context=mx.cpu())
    mod.fit(train, num_epoch=10, optimizer="adam",
            optimizer_params={"learning_rate": 0.005},
            initializer=mx.initializer.Xavier(magnitude=2.0),
            eval_data=val)
    score = dict(mod.score(val, "acc"))
    acc = next(iter(score.values()))
    logging.info("val accuracy %.3f", acc)
    assert acc > 0.8, score

    # predict + submission over the validation set (reference
    # predict_dsb.py runs the same batch loop over test.rec)
    val.reset()
    probs = []
    for batch in val:
        out = mod.predict_batch(batch) if hasattr(mod, "predict_batch") \
            else None
        if out is None:
            mod.forward(batch, is_train=False)
            out = mod.get_outputs()[0].asnumpy()
        probs.append(out[:out.shape[0] - batch.pad]
                     if batch.pad else out)
    preds = np.concatenate(probs)
    sub = os.path.join(tmp, "submission.csv")
    gen_sub(preds, os.path.join(tmp, "va.lst"), sub)

    with open(sub) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["image"] + CLASSES
    assert len(rows) - 1 == len(preds)
    body = np.array([[float(x) for x in r[1:]] for r in rows[1:]])
    np.testing.assert_allclose(body.sum(axis=1), 1.0, atol=1e-3)
    print("kaggle ndsb1 OK")


if __name__ == "__main__":
    main()
