#!/usr/bin/env python
"""CNN text classification (reference example/cnn_text_classification):
embedding -> parallel conv filters over the token axis -> max-over-time
pooling -> concat -> dense, Kim-2014 style, on a synthetic
phrase-detection task.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

SEQ_LEN = 20
VOCAB = 50
EMBED = 16


def build_net():
    data = mx.sym.Variable("data")                       # (N, T)
    embed = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                             name="embed")               # (N, T, E)
    x = mx.sym.Reshape(embed, shape=(-1, 1, SEQ_LEN, EMBED))
    pooled = []
    for k in (3, 4, 5):
        c = mx.sym.Convolution(x, kernel=(k, EMBED), num_filter=8,
                               name="conv%d" % k)        # (N, 8, T-k+1, 1)
        c = mx.sym.Activation(c, act_type="relu")
        p = mx.sym.Pooling(c, kernel=(SEQ_LEN - k + 1, 1),
                           pool_type="max")              # (N, 8, 1, 1)
        pooled.append(mx.sym.Flatten(p))
    h = mx.sym.Concat(*pooled, dim=1)
    h = mx.sym.Dropout(h, p=0.2)
    fc = mx.sym.FullyConnected(h, num_hidden=2, name="cls")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def make_data(rng, n):
    """Positive iff the trigram (7, 8, 9) occurs."""
    X = rng.randint(10, VOCAB, (n, SEQ_LEN))
    y = rng.randint(0, 2, n)
    for i in np.where(y == 1)[0]:
        pos = rng.randint(0, SEQ_LEN - 3)
        X[i, pos:pos + 3] = [7, 8, 9]
    return X.astype(np.float32), y.astype(np.float32)


def main(seed=0):
    rng = np.random.RandomState(seed)
    Xtr, ytr = make_data(rng, 768)
    Xte, yte = make_data(rng, 256)
    net = build_net()
    model = mx.model.FeedForward.create(
        net, X=mx.io.NDArrayIter(Xtr, ytr, batch_size=64, shuffle=True),
        num_epoch=8, optimizer="adam", learning_rate=2e-3, ctx=mx.cpu())
    acc = (model.predict(mx.io.NDArrayIter(Xte, yte, batch_size=64))
           .argmax(axis=1) == yte).mean()
    print("test accuracy: %.3f" % acc)
    assert acc > 0.85, acc
    print("text CNN OK")


if __name__ == "__main__":
    main()
