#!/usr/bin/env python
"""Bidirectional-LSTM sort (reference example/bi-lstm-sort): read a
sequence of digits and emit them sorted, using the fused bidirectional
``sym.RNN`` (the reference unrolled cells by hand) with a per-timestep
softmax head.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx
from mxnet_tpu.ops.seq import rnn_param_size

SEQ_LEN = 5
VOCAB = 8
HIDDEN = 32


def build_net(batch):
    data = mx.sym.Variable("data")          # (T, N) int ids
    embed = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=16,
                             name="embed")  # (T, N, 16)
    rnn = mx.sym.RNN(data=embed,
                     parameters=mx.sym.Variable("rnn_params"),
                     state=mx.sym.Variable("rnn_state"),
                     state_cell=mx.sym.Variable("rnn_state_cell"),
                     state_size=HIDDEN, num_layers=1, mode="lstm",
                     bidirectional=True, name="birnn")  # (T, N, 2H)
    flat = mx.sym.Reshape(rnn, shape=(batch * SEQ_LEN, 2 * HIDDEN))
    fc = mx.sym.FullyConnected(flat, num_hidden=VOCAB, name="cls")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def batches(rng, n, batch):
    X = rng.randint(0, VOCAB, (n, SEQ_LEN))
    Y = np.sort(X, axis=1)
    for i in range(0, n - batch + 1, batch):
        x = X[i:i + batch].T.astype(np.float32)          # (T, N)
        y = Y[i:i + batch].T.reshape(-1).astype(np.float32)
        yield x, y


def main(seed=0, epochs=12, batch=32):
    rng = np.random.RandomState(seed)
    net = build_net(batch)
    psize = rnn_param_size(1, 16, HIDDEN, True, "lstm")
    exe = net.simple_bind(mx.cpu(), data=(SEQ_LEN, batch),
                          rnn_params=(psize,),
                          rnn_state=(2, batch, HIDDEN),
                          rnn_state_cell=(2, batch, HIDDEN),
                          softmax_label=(SEQ_LEN * batch,))
    init = mx.init.Xavier()
    for name, arr in exe.arg_dict.items():
        if name.startswith(("embed", "cls", "rnn_params")):
            init(name if "params" not in name else "%s_weight" % name,
                 arr)
    updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=5e-3))
    skip = {"data", "softmax_label", "rnn_state", "rnn_state_cell"}

    for epoch in range(epochs):
        correct = total = 0
        for x, y in batches(rng, 512, batch):
            exe.arg_dict["data"][:] = x
            exe.arg_dict["softmax_label"][:] = y
            exe.forward(is_train=True)
            exe.backward()
            for i, name in enumerate(net.list_arguments()):
                if name in skip:
                    continue
                updater(i, exe.grad_dict[name], exe.arg_dict[name])
            pred = exe.outputs[0].asnumpy().argmax(axis=1)
            correct += (pred == y).sum()
            total += y.size
        acc = correct / total
    print("sorted-digit accuracy after %d epochs: %.3f" % (epochs, acc))
    assert acc > 0.7, acc
    print("bi-LSTM sort OK")


if __name__ == "__main__":
    main()
