#!/usr/bin/env python
"""Stochastic depth (reference example/stochastic-depth): residual
blocks whose bodies are randomly dropped during training and scaled by
their survival probability at inference — implemented as a CustomOp
(`DropPath`), the frontend-op extension point the reference version used
for its death-rate gating.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


@mx.operator.register("droppath")
class DropPathProp(mx.operator.CustomOpProp):
    """Bernoulli-gate the whole residual branch: train-time the branch
    is dropped (zeroed) with probability ``death_rate`` per batch;
    inference scales by the survival probability instead."""

    def __init__(self, death_rate="0.3", seed="0"):
        super().__init__(need_top_grad=True)
        self.death_rate = float(death_rate)
        self.rng = np.random.RandomState(int(seed))

    def create_operator(self, ctx, in_shapes, in_dtypes):
        prop = self

        class DropPath(mx.operator.CustomOp):
            def __init__(op):
                op.gate = 1.0

            def forward(op, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                if is_train:
                    op.gate = float(prop.rng.rand() >= prop.death_rate)
                    out = x * op.gate
                else:
                    out = x * (1.0 - prop.death_rate)
                op.assign(out_data[0], req[0], out)

            def backward(op, req, out_grad, in_data, out_data, in_grad,
                         aux):
                op.assign(in_grad[0], req[0],
                          out_grad[0].asnumpy() * op.gate)

        return DropPath()


def res_block(x, n_hidden, death_rate, idx):
    body = mx.sym.FullyConnected(x, num_hidden=n_hidden,
                                 name="b%d_fc" % idx)
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Custom(body, op_type="droppath",
                         death_rate=str(death_rate), seed=str(idx),
                         name="b%d_drop" % idx)
    return x + body


def main(seed=0, death_rate=0.3):
    rng = np.random.RandomState(seed)
    n, d = 512, 16
    y = rng.randint(0, 2, n).astype(np.float32)
    X = (rng.randn(n, d) + y[:, None] * 1.6).astype(np.float32)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="stem")
    for i in range(3):
        net = res_block(net, 32, death_rate, i)
    net = mx.sym.FullyConnected(net, num_hidden=2, name="cls")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    model = mx.model.FeedForward.create(
        net, X=mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True),
        num_epoch=8, learning_rate=0.1, ctx=mx.cpu())
    acc = (model.predict(mx.io.NDArrayIter(X, batch_size=64))
           .argmax(axis=1) == y).mean()
    print("accuracy with stochastic depth: %.3f" % acc)
    assert acc > 0.9, acc
    print("stochastic depth OK")


if __name__ == "__main__":
    main()
