#!/usr/bin/env python
"""Bayesian methods via SGLD (reference example/bayesian-methods):
stochastic gradient Langevin dynamics samples the posterior of a
Bayesian linear regression — the optimizer IS the sampler. After
burn-in, the iterate distribution matches the analytic posterior
N((X'X + I)^-1 X'y, sigma^2 (X'X + I)^-1).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def main(seed=0, n=256, d=4, sigma=0.5):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(d).astype(np.float32)
    X = rng.randn(n, d).astype(np.float32)
    yv = (X @ w_true + rng.randn(n) * sigma).astype(np.float32)

    # posterior of w under unit gaussian prior + gaussian likelihood
    prec = X.T @ X / sigma**2 + np.eye(d)
    cov = np.linalg.inv(prec)
    mean = cov @ X.T @ yv / sigma**2

    # loss = ||y - Xw||^2 / (2 sigma^2): its gradient is the negative
    # log-likelihood gradient; SGLD's wd term supplies the prior
    data = mx.sym.Variable("data")
    pred = mx.sym.FullyConnected(data, num_hidden=1, no_bias=True,
                                 name="w")
    net = mx.sym.LinearRegressionOutput(
        data=pred, label=mx.sym.Variable("label"), name="out")
    exe = net.simple_bind(mx.cpu(), data=(n, d), label=(n, 1))
    exe.arg_dict["data"][:] = X
    exe.arg_dict["label"][:] = yv.reshape(-1, 1)
    exe.arg_dict["w_weight"][:] = np.zeros((1, d), np.float32)

    # LinearRegressionOutput backward yields the summed gradient
    # X'(Xw - y); scaling by 1/sigma^2 makes it the negative
    # log-likelihood gradient, and wd=1 adds the unit-gaussian prior
    opt = mx.optimizer.create("sgld", learning_rate=2e-4, wd=1.0,
                              rescale_grad=1.0 / sigma**2)
    updater = mx.optimizer.get_updater(opt)

    samples = []
    for step in range(6000):
        exe.forward(is_train=True)
        exe.backward()
        updater(0, exe.grad_dict["w_weight"], exe.arg_dict["w_weight"])
        if step >= 2000 and step % 2 == 0:
            samples.append(exe.arg_dict["w_weight"].asnumpy().ravel())
    S = np.stack(samples)

    mean_err = np.abs(S.mean(axis=0) - mean).max()
    std_err = np.abs(S.std(axis=0) - np.sqrt(np.diag(cov))).max()
    print("posterior mean err %.4f  std err %.4f (post std ~%.3f)"
          % (mean_err, std_err, np.sqrt(np.diag(cov)).mean()))
    assert mean_err < 0.1, mean_err
    assert std_err < 0.05, std_err
    print("SGLD OK")


if __name__ == "__main__":
    main()
