#!/usr/bin/env python
"""Module API walkthrough (reference example/module + python-howto):
the manual bind/init/forward/backward/update loop, fit(), checkpointing,
and BucketingModule — the intermediate-level API tour the reference's
notebooks gave.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def build():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main(seed=0):
    rng = np.random.RandomState(seed)
    n, d = 512, 10
    y = rng.randint(0, 2, n).astype(np.float32)
    X = (rng.randn(n, d) + y[:, None] * 1.8).astype(np.float32)
    net = build()

    # --- 1. the manual loop -------------------------------------------
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.2})
    metric = mx.metric.create("acc")
    for epoch in range(5):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(metric, batch.label)
    name, acc = metric.get()
    print("manual loop %s: %.3f" % (name, acc))
    assert acc > 0.9, acc

    # --- 2. fit() + checkpoint ----------------------------------------
    prefix = os.path.join(tempfile.mkdtemp(), "howto")
    mod2 = mx.mod.Module(net, context=mx.cpu())
    it.reset()
    mod2.fit(it, num_epoch=3,
             optimizer_params={"learning_rate": 0.2},
             epoch_end_callback=mx.callback.do_checkpoint(prefix))
    sym_loaded, arg_params, aux_params = \
        mx.model.load_checkpoint(prefix, 3)
    assert sym_loaded.tojson() == net.tojson()
    assert set(arg_params) == {"fc1_weight", "fc1_bias", "fc2_weight",
                               "fc2_bias"}
    print("fit + checkpoint OK (%s-0003.params)" % prefix)

    # --- 3. predict with loaded params --------------------------------
    mod3 = mx.mod.Module(net, context=mx.cpu())
    pit = mx.io.NDArrayIter(X, y, batch_size=64)
    mod3.bind(data_shapes=pit.provide_data, for_training=False)
    mod3.set_params(arg_params, aux_params)
    preds = mod3.predict(pit)
    acc = (preds.asnumpy().argmax(axis=1) == y).mean()
    print("restored-module accuracy: %.3f" % acc)
    assert acc > 0.9, acc
    print("module howto OK")


if __name__ == "__main__":
    main()
