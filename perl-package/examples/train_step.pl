#!/usr/bin/perl
# Load a reference-format checkpoint, run inference, then one SGD step —
# the frontend-parity demo (the reference's R-package "predict + train"
# story over the C API, in Perl).
#
# Usage: train_step.pl <symbol.json> <params-file> <data.csv> <label.csv> <lr>
# Prints: "probs=<comma list>" (pre-update inference on the batch),
#         "probs_after=<comma list>" (after one SGD step),
#         "loss_before=<v> loss_after=<v>".
use strict;
use warnings;
use FindBin;
use lib "$FindBin::Bin/../lib", "$FindBin::Bin/../blib";
use MXNetTPU;

my ($sym_file, $param_file, $data_csv, $label_csv, $lr) = @ARGV;
die "usage: $0 sym.json params data.csv label.csv lr\n" unless $lr;

sub read_csv {
    my ($f) = @_;
    open my $fh, '<', $f or die "open $f: $!";
    my @rows;
    while (<$fh>) {
        chomp;
        push @rows, [split /,/];
    }
    close $fh;
    return \@rows;
}

my $X = read_csv($data_csv);
my $y = read_csv($label_csv);
my $batch = scalar @$X;
my $feat  = scalar @{ $X->[0] };

my $sym = MXNetTPU::Symbol->load($sym_file);
my $params = MXNetTPU::NDArray->load_params($param_file);
my $shapes = $sym->infer_shape("data", $batch, $feat);

my $exe = $sym->simple_bind(for_training => 1, data => ["data", $batch, $feat]);

# weights from the checkpoint (container keys are "arg:<name>")
my @weight_names;
for my $name ($sym->list_arguments) {
    next if $name eq 'data' || $name eq 'softmax_label';
    my $packed = $params->{"arg:$name"} // $params->{$name}
      or die "checkpoint missing $name";
    $exe->set_arg($name, $packed);
    push @weight_names, $name;
}

my @flat_x = map { @$_ } @$X;
my @flat_y = map { $_->[0] } @$y;
$exe->set_arg("data",          pack("f*", @flat_x));
$exe->set_arg("softmax_label", pack("f*", @flat_y));

sub xent {
    my ($probs) = @_;
    my $loss = 0;
    for my $i (0 .. $batch - 1) {
        my $p = $probs->[ $i * 2 + $flat_y[$i] ];
        $loss -= log($p > 1e-12 ? $p : 1e-12);
    }
    return $loss / $batch;
}

# inference before the update
$exe->forward(0);
my @probs = unpack("f*", $exe->get_output(0, $batch * 2));
printf "probs=%s\n", join(",", map { sprintf "%.6f", $_ } @probs[0 .. 5]);
printf "loss_before=%.6f\n", xent(\@probs);

# one SGD step: forward(train) + backward + host-side update
$exe->forward(1);
$exe->backward;
for my $name (@weight_names) {
    my $dims = $shapes->{$name};
    my $size = 1;
    $size *= $_ for @$dims;
    my @w = unpack("f*", $params->{"arg:$name"} // $params->{$name});
    my @g = unpack("f*", $exe->get_grad($name, $size));
    $w[$_] -= $lr * $g[$_] for 0 .. $size - 1;
    $exe->set_arg($name, pack("f*", @w));
}

$exe->forward(0);
my @probs2 = unpack("f*", $exe->get_output(0, $batch * 2));
printf "probs_after=%s\n", join(",", map { sprintf "%.6f", $_ } @probs2[0 .. 5]);
printf "loss_after=%.6f\n", xent(\@probs2);
