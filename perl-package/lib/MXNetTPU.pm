package MXNetTPU;

# Perl frontend over the mxnet_tpu C ABI — the image's non-Python
# binding, playing the role the reference's R-package played over its
# C API (R-package/R/*.R over .Call stubs into src/c_api/c_api.cc):
# object classes here, thin XSUBs in MXNetTPU.xs.
#
# Float tensors are Perl strings packed with pack("f*", @values).

use strict;
use warnings;
use DynaLoader ();

our $VERSION = '0.1';
our @ISA = ('DynaLoader');

sub dl_load_flags { 0x01 }    # RTLD_GLOBAL: libmxtpu_predict symbols

__PACKAGE__->bootstrap($VERSION);

# ---------------------------------------------------------------------------
package MXNetTPU::Symbol;

sub load_json {
    my ($class, $json) = @_;
    my $h = MXNetTPU::symbol_load_json($json);
    return bless { handle => $h }, $class;
}

sub load {
    my ($class, $fname) = @_;
    my $h = MXNetTPU::symbol_load($fname);
    return bless { handle => $h }, $class;
}

sub save {
    my ($self, $fname) = @_;
    MXNetTPU::symbol_save($self->{handle}, $fname);
}

# Gradient symbol wrt the named arguments (MXSymbolGrad)
sub grad {
    my ($self, @wrt) = @_;
    my $h = MXNetTPU::symbol_grad($self->{handle}, @wrt);
    return bless { handle => $h }, ref($self);
}

sub tojson { MXNetTPU::symbol_to_json($_[0]{handle}) }

sub list_arguments {
    my ($self) = @_;
    return MXNetTPU::symbol_list_arguments($self->{handle});
}

sub infer_shape {
    my ($self, $data_name, @dims) = @_;
    my @shapes =
      MXNetTPU::symbol_infer_shape($self->{handle}, $data_name, @dims);
    my @args = $self->list_arguments;
    my %by_name;
    $by_name{ $args[$_] } = $shapes[$_] for 0 .. $#args;
    return \%by_name;
}

sub simple_bind {
    my ($self, %opt) = @_;
    my $train = $opt{for_training} ? 1 : 0;
    my ($name, @dims) = @{ $opt{data} };
    my $h =
      MXNetTPU::executor_simple_bind($self->{handle}, $train, $name, @dims);
    return bless { handle => $h, symbol => $self }, 'MXNetTPU::Executor';
}

sub DESTROY { MXNetTPU::symbol_free($_[0]{handle}) if $_[0]{handle} }

# ---------------------------------------------------------------------------
package MXNetTPU::Executor;

sub set_arg {
    my ($self, $name, $packed) = @_;
    MXNetTPU::executor_set_arg($self->{handle}, $name, $packed);
}

sub forward {
    my ($self, $is_train) = @_;
    MXNetTPU::executor_forward($self->{handle}, $is_train ? 1 : 0);
}

sub backward { MXNetTPU::executor_backward($_[0]{handle}) }

sub get_output {
    my ($self, $index, $size) = @_;
    return MXNetTPU::executor_get_output($self->{handle}, $index, $size);
}

sub get_grad {
    my ($self, $name, $size) = @_;
    return MXNetTPU::executor_get_grad($self->{handle}, $name, $size);
}

sub DESTROY { MXNetTPU::executor_free($_[0]{handle}) if $_[0]{handle} }


# Registered optimizer over the C surface (MXOptimizerCreateOptimizer):
# per-index state lives on the native handle; lr/wd are per-call.
package MXNetTPU::Optimizer;

sub create {
    my ($class, $name, %params) = @_;
    my $h = MXNetTPU::optimizer_create($name, %params);
    return bless { handle => $h }, $class;
}

sub update {
    my ($self, $index, $weight, $grad, $lr, $wd) = @_;
    MXNetTPU::optimizer_update($self->{handle}, $index, $weight, $grad,
                               $lr, $wd // 0.0);
}

sub DESTROY { MXNetTPU::optimizer_free($_[0]{handle}) if $_[0]{handle} }

# ---------------------------------------------------------------------------
package MXNetTPU::NDArray;

# Load a reference-format checkpoint container: returns
# { name => packed-float-string }.
sub load_params {
    my ($class, $fname) = @_;
    my %pairs = MXNetTPU::nd_load($fname);
    return \%pairs;
}

# Device array from a Perl list (f32, cpu): used with the optimizer
# surface, which takes NDArray handles.
sub from_list {
    my ($class, $values, $shape) = @_;
    $shape //= [scalar @$values];
    my $h = MXNetTPU::nd_create(pack("f*", @$values), @$shape);
    my $n = 1; $n *= $_ for @$shape;
    return bless { handle => $h, size => $n }, $class;
}

sub values {
    my ($self) = @_;
    return unpack("f*", MXNetTPU::nd_values($self->{handle},
                                            $self->{size}));
}

sub DESTROY { MXNetTPU::nd_free($_[0]{handle}) if $_[0]{handle} }

1;
__END__

=head1 NAME

MXNetTPU - Perl frontend for the mxnet_tpu TPU-native framework

=head1 SYNOPSIS

  use MXNetTPU;
  my $sym = MXNetTPU::Symbol->load("model-symbol.json");
  my $params = MXNetTPU::NDArray->load_params("model-0001.params");
  my $exe = $sym->simple_bind(for_training => 1,
                              data => ["data", 32, 10]);
  $exe->set_arg("fc1_weight", $params->{"arg:fc1_weight"});
  $exe->set_arg("data", pack("f*", @x));
  $exe->forward(1);
  my @probs = unpack("f*", $exe->get_output(0, 32 * 2));
  $exe->backward;
  my @grad = unpack("f*", $exe->get_grad("fc1_weight", 160));

=cut
