/*
 * XS glue: Perl -> libmxtpu_predict.so C ABI.
 *
 * The reference shipped R/Scala/Matlab frontends over its ~110-function
 * C API (R-package/src, scala-package native JNI); this is the same
 * pattern for Perl, the non-Python runtime available in this image:
 * thin XSUBs over include/mxnet_tpu/c_api.h, with the object model
 * (Symbol/Executor/NDArray classes) living in lib/MXNetTPU.pm, exactly
 * as R kept its classes in R code over .Call stubs.
 *
 * Handles cross as IVs (pointer-sized integers); float buffers cross as
 * Perl strings packed with pack("f*", ...), the idiomatic Perl binary
 * representation.
 */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include <stdlib.h>

#include <mxnet_tpu/c_api.h>

static void croak_on(pTHX_ int rc, const char *what) {
  if (rc != 0) croak("%s failed: %s", what, MXGetLastError());
}

MODULE = MXNetTPU  PACKAGE = MXNetTPU  PREFIX = mxtpu_

PROTOTYPES: DISABLE

const char *
mxtpu_last_error()
  CODE:
    RETVAL = MXGetLastError();
  OUTPUT:
    RETVAL

IV
mxtpu_symbol_load_json(json)
    const char *json
  CODE:
    SymbolHandle h;
    croak_on(aTHX_ MXSymbolCreateFromJSON(json, &h), "MXSymbolCreateFromJSON");
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

const char *
mxtpu_symbol_to_json(sym)
    IV sym
  CODE:
    const char *json;
    croak_on(aTHX_ MXSymbolSaveToJSON(INT2PTR(SymbolHandle, sym), &json),
             "MXSymbolSaveToJSON");
    RETVAL = json;
  OUTPUT:
    RETVAL

void
mxtpu_symbol_list_arguments(sym)
    IV sym
  PPCODE:
    mx_uint n;
    const char **names;
    croak_on(aTHX_ MXSymbolListArguments(INT2PTR(SymbolHandle, sym), &n,
                                         &names),
             "MXSymbolListArguments");
    EXTEND(SP, n);
    for (mx_uint i = 0; i < n; ++i)
      PUSHs(sv_2mortal(newSVpv(names[i], 0)));

void
mxtpu_symbol_infer_shape(sym, data_name, ...)
    IV sym
    const char *data_name
  PPCODE:
    /* remaining stack items: the data dims; returns one arrayref of dims
     * per argument, in list_arguments order */
    mx_uint ndim = (mx_uint)(items - 2);
    if (ndim > 16) croak("infer_shape: at most 16 data dims, got %u", ndim);
    mx_uint indptr[2] = {0, ndim};
    mx_uint dims[16];
    for (mx_uint i = 0; i < ndim; ++i)
      dims[i] = (mx_uint)SvUV(ST(2 + i));
    const char *keys[1] = {data_name};
    mx_uint in_n, out_n;
    const mx_uint *in_ndim, *out_ndim;
    const mx_uint **in_sh, **out_sh;
    croak_on(aTHX_ MXSymbolInferShape(INT2PTR(SymbolHandle, sym), 1, keys,
                                      indptr, dims, &in_n, &in_ndim, &in_sh,
                                      &out_n, &out_ndim, &out_sh),
             "MXSymbolInferShape");
    EXTEND(SP, in_n);
    for (mx_uint i = 0; i < in_n; ++i) {
      AV *av = newAV();
      for (mx_uint d = 0; d < in_ndim[i]; ++d)
        av_push(av, newSVuv(in_sh[i][d]));
      PUSHs(sv_2mortal(newRV_noinc((SV *)av)));
    }

void
mxtpu_symbol_free(sym)
    IV sym
  CODE:
    MXSymbolFree(INT2PTR(SymbolHandle, sym));

void
mxtpu_nd_load(fname)
    const char *fname
  PPCODE:
    /* returns flat list: name0, packed0, name1, packed1, ... */
    mx_uint n, nn;
    NDArrayHandle *arrs;
    const char **names;
    croak_on(aTHX_ MXNDArrayLoad(fname, &n, &arrs, &nn, &names),
             "MXNDArrayLoad");
    EXTEND(SP, 2 * (int)n);
    for (mx_uint i = 0; i < n; ++i) {
      mx_uint ndim;
      const mx_uint *dims;
      MXNDArrayGetShape(arrs[i], &ndim, &dims);
      mx_uint size = 1;
      for (mx_uint d = 0; d < ndim; ++d) size *= dims[d];
      /* mortal up-front: a croak below must not leak the SV */
      SV *buf = sv_2mortal(newSV(size * sizeof(mx_float)));
      SvPOK_on(buf);
      SvCUR_set(buf, size * sizeof(mx_float));
      if (MXNDArraySyncCopyToCPU(arrs[i], (mx_float *)SvPVX(buf), size)
          != 0) {
        MXNDArrayListFree(arrs, n, names);  /* no native leak on croak */
        croak("MXNDArraySyncCopyToCPU failed: %s", MXGetLastError());
      }
      PUSHs(sv_2mortal(newSVpv(nn > i ? names[i] : "", 0)));
      PUSHs(buf);
    }
    MXNDArrayListFree(arrs, n, names);

IV
mxtpu_executor_simple_bind(sym, for_training, data_name, ...)
    IV sym
    int for_training
    const char *data_name
  CODE:
    mx_uint ndim = (mx_uint)(items - 3);
    if (ndim > 16) croak("simple_bind: at most 16 data dims, got %u", ndim);
    mx_uint indptr[2] = {0, ndim};
    mx_uint dims[16];
    for (mx_uint i = 0; i < ndim; ++i)
      dims[i] = (mx_uint)SvUV(ST(3 + i));
    const char *keys[1] = {data_name};
    ExecutorHandle exe;
    croak_on(aTHX_ MXExecutorSimpleBind(INT2PTR(SymbolHandle, sym), 1, 0, 1,
                                        keys, indptr, dims, for_training,
                                        &exe),
             "MXExecutorSimpleBind");
    RETVAL = PTR2IV(exe);
  OUTPUT:
    RETVAL

void
mxtpu_executor_set_arg(exe, name, packed)
    IV exe
    const char *name
    SV *packed
  CODE:
    STRLEN len;
    const char *buf = SvPV(packed, len);
    croak_on(aTHX_ MXExecutorSetArg(INT2PTR(ExecutorHandle, exe), name,
                                    (const mx_float *)buf,
                                    (mx_uint)(len / sizeof(mx_float))),
             "MXExecutorSetArg");

void
mxtpu_executor_forward(exe, is_train)
    IV exe
    int is_train
  CODE:
    croak_on(aTHX_ MXExecutorForward(INT2PTR(ExecutorHandle, exe), is_train),
             "MXExecutorForward");

void
mxtpu_executor_backward(exe)
    IV exe
  CODE:
    croak_on(aTHX_ MXExecutorBackward(INT2PTR(ExecutorHandle, exe)),
             "MXExecutorBackward");

SV *
mxtpu_executor_get_output(exe, index, size)
    IV exe
    unsigned index
    unsigned size
  CODE:
    mx_float *tmp = (mx_float *)malloc((size_t)size * sizeof(mx_float));
    if (!tmp) croak("out of memory");
    if (MXExecutorGetOutput(INT2PTR(ExecutorHandle, exe), index, tmp, size)
        != 0) {
      free(tmp);
      croak("MXExecutorGetOutput failed: %s", MXGetLastError());
    }
    RETVAL = newSVpvn((const char *)tmp, (STRLEN)size * sizeof(mx_float));
    free(tmp);
  OUTPUT:
    RETVAL

SV *
mxtpu_executor_get_grad(exe, name, size)
    IV exe
    const char *name
    unsigned size
  CODE:
    mx_float *tmp = (mx_float *)malloc((size_t)size * sizeof(mx_float));
    if (!tmp) croak("out of memory");
    if (MXExecutorGetGrad(INT2PTR(ExecutorHandle, exe), name, tmp, size)
        != 0) {
      free(tmp);
      croak("MXExecutorGetGrad failed: %s", MXGetLastError());
    }
    RETVAL = newSVpvn((const char *)tmp, (STRLEN)size * sizeof(mx_float));
    free(tmp);
  OUTPUT:
    RETVAL

void
mxtpu_executor_free(exe)
    IV exe
  CODE:
    MXExecutorFree(INT2PTR(ExecutorHandle, exe));

IV
mxtpu_symbol_grad(sym, ...)
    IV sym
  CODE:
    /* remaining stack items are wrt argument names */
    mx_uint n = (mx_uint)(items - 1);
    const char **wrt = (const char **)malloc(n * sizeof(char *));
    mx_uint i;
    for (i = 0; i < n; ++i) wrt[i] = SvPV_nolen(ST(1 + i));
    SymbolHandle out;
    int rc = MXSymbolGrad(INT2PTR(SymbolHandle, sym), n, wrt, &out);
    free(wrt);
    croak_on(aTHX_ rc, "MXSymbolGrad");
    RETVAL = PTR2IV(out);
  OUTPUT:
    RETVAL

void
mxtpu_symbol_save(sym, fname)
    IV sym
    const char *fname
  CODE:
    croak_on(aTHX_ MXSymbolSaveToFile(INT2PTR(SymbolHandle, sym), fname),
             "MXSymbolSaveToFile");

IV
mxtpu_symbol_load(fname)
    const char *fname
  CODE:
    SymbolHandle h;
    croak_on(aTHX_ MXSymbolCreateFromFile(fname, &h),
             "MXSymbolCreateFromFile");
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

IV
mxtpu_optimizer_create(name, ...)
    const char *name
  CODE:
    /* remaining stack items are key,value string pairs */
    if ((items - 1) % 2 != 0)
      croak("optimizer_create: odd number of key/value items");
    OptimizerCreator creator;
    croak_on(aTHX_ MXOptimizerFindCreator(name, &creator),
             "MXOptimizerFindCreator");
    mx_uint n = (mx_uint)((items - 1) / 2);
    const char **keys = (const char **)malloc(n * sizeof(char *));
    const char **vals = (const char **)malloc(n * sizeof(char *));
    mx_uint i;
    for (i = 0; i < n; ++i) {
      keys[i] = SvPV_nolen(ST(1 + 2 * i));
      vals[i] = SvPV_nolen(ST(2 + 2 * i));
    }
    OptimizerHandle h;
    int rc = MXOptimizerCreateOptimizer(creator, n, keys, vals, &h);
    free(keys);
    free(vals);
    croak_on(aTHX_ rc, "MXOptimizerCreateOptimizer");
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

void
mxtpu_optimizer_update(opt, index, weight, grad, lr, wd)
    IV opt
    IV index
    IV weight
    IV grad
    double lr
    double wd
  CODE:
    croak_on(aTHX_ MXOptimizerUpdate(INT2PTR(OptimizerHandle, opt),
                                     (int)index,
                                     INT2PTR(NDArrayHandle, weight),
                                     INT2PTR(NDArrayHandle, grad),
                                     (mx_float)lr, (mx_float)wd),
             "MXOptimizerUpdate");

void
mxtpu_optimizer_free(opt)
    IV opt
  CODE:
    MXOptimizerFree(INT2PTR(OptimizerHandle, opt));

void
mxtpu_random_seed(seed)
    IV seed
  CODE:
    croak_on(aTHX_ MXRandomSeed((int)seed), "MXRandomSeed");

IV
mxtpu_nd_create(packed, ...)
    SV *packed
  CODE:
    /* packed float data + shape dims on the stack */
    mx_uint ndim = (mx_uint)(items - 1);
    if (ndim == 0) croak("nd_create: shape required");
    mx_uint *dims = (mx_uint *)malloc(ndim * sizeof(mx_uint));
    mx_uint i, size = 1;
    for (i = 0; i < ndim; ++i) {
      dims[i] = (mx_uint)SvIV(ST(1 + i));
      size *= dims[i];
    }
    STRLEN len;
    const char *buf = SvPV(packed, len);
    if (len != size * sizeof(mx_float)) {
      free(dims);
      croak("nd_create: packed %lu bytes, shape wants %lu",
            (unsigned long)len, (unsigned long)(size * sizeof(mx_float)));
    }
    NDArrayHandle h;
    int rc = MXNDArrayCreate(dims, ndim, 1, 0, &h);
    free(dims);
    croak_on(aTHX_ rc, "MXNDArrayCreate");
    croak_on(aTHX_ MXNDArraySyncCopyFromCPU(h, (const mx_float *)buf,
                                            size),
             "MXNDArraySyncCopyFromCPU");
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

SV *
mxtpu_nd_values(handle, size)
    IV handle
    IV size
  CODE:
    SV *buf = newSV((STRLEN)size * sizeof(mx_float));
    SvPOK_on(buf);
    SvCUR_set(buf, (STRLEN)size * sizeof(mx_float));
    if (MXNDArraySyncCopyToCPU(INT2PTR(NDArrayHandle, handle),
                               (mx_float *)SvPVX(buf),
                               (mx_uint)size) != 0) {
      SvREFCNT_dec(buf);
      croak("MXNDArraySyncCopyToCPU failed: %s", MXGetLastError());
    }
    RETVAL = buf;
  OUTPUT:
    RETVAL

void
mxtpu_nd_free(handle)
    IV handle
  CODE:
    MXNDArrayFree(INT2PTR(NDArrayHandle, handle));
