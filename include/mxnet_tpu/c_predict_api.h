/*!
 * C predict API — the deployment ABI for non-Python frontends.
 *
 * Mirrors the reference's include/mxnet/c_predict_api.h:60-170 surface
 * (MXPredCreate / MXPredSetInput / MXPredForward / MXPredGetOutput /
 * MXPredFree plus the MXNDList* param-blob readers and the
 * -1 + MXGetLastError() error convention of src/c_api/c_api_error.h).
 * The implementation (src/capi/c_predict_api.cc) hosts the TPU runtime
 * by embedding CPython and driving mxnet_tpu.predictor.Predictor; the
 * compute itself is the XLA-compiled graph, so the embedding layer is
 * control-plane only.
 */
#ifndef MXNET_TPU_C_PREDICT_API_H_
#define MXNET_TPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *PredictorHandle;
typedef void *NDListHandle;
typedef uint32_t mx_uint;
typedef float mx_float;

/*! \brief last error message from a failed (-1) call; thread-local. */
const char *MXGetLastError(void);

/*!
 * \brief Create a predictor from a symbol JSON string and a parameter
 * blob (the bytes of a saved .params file).
 * \param symbol_json_str symbol JSON
 * \param param_bytes param file bytes
 * \param param_size length of param_bytes
 * \param dev_type 1=cpu, 2=tpu
 * \param dev_id device ordinal
 * \param num_input_nodes number of dynamic inputs
 * \param input_keys input names
 * \param input_shape_indptr offsets into input_shape_data per input
 *        (length num_input_nodes+1)
 * \param input_shape_data concatenated input dims
 * \param out created handle
 * \return 0 on success, -1 on failure
 */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out);

/*! \brief Re-bind with new input shapes, sharing weights. */
int MXPredReshape(mx_uint num_input_nodes, const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data, PredictorHandle handle,
                  PredictorHandle *out);

/*! \brief Shape of output index; pointers valid until next call/Free. */
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim);

/*! \brief Copy input data (row-major float32 of the bound shape). */
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size);

/*! \brief Run the forward pass. */
int MXPredForward(PredictorHandle handle);

/*! \brief Copy output index into user buffer of `size` floats. */
int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float *data,
                    mx_uint size);

/*! \brief Free the predictor. */
int MXPredFree(PredictorHandle handle);

/*! \brief Load a saved NDArray container (e.g. mean image file). */
int MXNDListCreate(const char *nd_file_bytes, int nd_file_size,
                   NDListHandle *out, mx_uint *out_length);

/*! \brief Get entry `index`: name, data pointer, shape. Pointers valid
 * until MXNDListFree. */
int MXNDListGet(NDListHandle handle, mx_uint index, const char **out_key,
                const mx_float **out_data, const mx_uint **out_shape,
                mx_uint *out_ndim);

/*! \brief Free the list. */
int MXNDListFree(NDListHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_PREDICT_API_H_ */
