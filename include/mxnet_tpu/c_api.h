/*!
 * Core C API — the training-capable ABI subset for non-Python frontends.
 *
 * The reference exposed ~110 MX* functions (include/mxnet/c_api.h) that
 * the R/Scala/Matlab bindings consumed: NDArray create/copy/save/load,
 * symbol compose/infer, executor bind/forward/backward, KVStore. This
 * header is the re-designed equivalent over the TPU runtime: the subset
 * that a frontend needs to build tensors, load/compose symbols, run
 * training steps, and read gradients. Deployment-only clients should
 * prefer c_predict_api.h.
 *
 * Conventions follow the reference (src/c_api/c_api_error.h): every
 * function returns 0 on success, -1 on failure with the message
 * available from MXGetLastError() (thread-local).
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef uint32_t mx_uint;
typedef float mx_float;

const char *MXGetLastError(void);

/* ---- NDArray ---------------------------------------------------------- */

/*! \brief Create an f32 NDArray of the given shape (dev_type 1=cpu,
 * 2=tpu), zero-initialized. */
int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
/*! \brief Shape query; pointers valid until the next call on this
 * handle or Free. */
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_ndim,
                      const mx_uint **out_pdata);
/*! \brief Blocking host->device copy of `size` floats. */
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const mx_float *data,
                             mx_uint size);
/*! \brief Blocking device->host copy of `size` floats. */
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, mx_float *data,
                           mx_uint size);
int MXNDArrayWaitAll(void);
/*! \brief Save named arrays to the reference-compatible container. */
int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args, const char **keys);
/*! \brief Load a container; returns parallel arrays of handles and
 * names (valid until MXNDArrayListFree). */
int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names);
int MXNDArrayListFree(NDArrayHandle *arr, mx_uint size,
                      const char **names);

/* ---- Symbol ----------------------------------------------------------- */

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
/*! \brief Serialize; the returned string is valid until the next call
 * on this handle or Free. */
int MXSymbolSaveToJSON(SymbolHandle handle, const char **out_json);
/*! \brief List argument names; valid until next call/Free. */
int MXSymbolListArguments(SymbolHandle handle, mx_uint *out_size,
                          const char ***out_array);
int MXSymbolListOutputs(SymbolHandle handle, mx_uint *out_size,
                        const char ***out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle handle, mx_uint *out_size,
                                const char ***out_array);
/*! \brief Shape inference from named input shapes. Returns per-argument
 * shapes (csr layout: ind[i]..ind[i+1] into data). Buffers valid until
 * next call/Free. */
int MXSymbolInferShape(SymbolHandle handle, mx_uint num_args,
                       const char **keys, const mx_uint *arg_ind_ptr,
                       const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data);
int MXSymbolFree(SymbolHandle handle);

/* ---- Executor --------------------------------------------------------- */

/*! \brief simple_bind: infer shapes from named inputs, allocate
 * args/grads/aux, bind (grad_req "write" when for_training != 0). */
int MXExecutorSimpleBind(SymbolHandle symbol, int dev_type, int dev_id,
                         mx_uint num_args, const char **keys,
                         const mx_uint *arg_ind_ptr,
                         const mx_uint *arg_shape_data, int for_training,
                         ExecutorHandle *out);
/*! \brief Copy data into a named argument (input or parameter). */
int MXExecutorSetArg(ExecutorHandle handle, const char *name,
                     const mx_float *data, mx_uint size);
int MXExecutorForward(ExecutorHandle handle, int is_train);
/*! \brief Backward with implicit all-ones head gradients. */
int MXExecutorBackward(ExecutorHandle handle);
/*! \brief Number of outputs. */
int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size);
/*! \brief Copy output `index` to host (`size` floats must match). */
int MXExecutorGetOutput(ExecutorHandle handle, mx_uint index,
                        mx_float *data, mx_uint size);
/*! \brief Copy the gradient of argument `name` to host. */
int MXExecutorGetGrad(ExecutorHandle handle, const char *name,
                      mx_float *data, mx_uint size);
int MXExecutorFree(ExecutorHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_API_H_ */
