/*!
 * Core C API — the training-capable ABI subset for non-Python frontends.
 *
 * The reference exposed ~110 MX* functions (include/mxnet/c_api.h) that
 * the R/Scala/Matlab bindings consumed: NDArray create/copy/save/load,
 * symbol compose/infer, executor bind/forward/backward, KVStore. This
 * header is the re-designed equivalent over the TPU runtime: the subset
 * that a frontend needs to build tensors, load/compose symbols, run
 * training steps, and read gradients. Deployment-only clients should
 * prefer c_predict_api.h.
 *
 * Conventions follow the reference (src/c_api/c_api_error.h): every
 * function returns 0 on success, -1 on failure with the message
 * available from MXGetLastError() (thread-local).
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef void *FunctionHandle;
typedef void *AtomicSymbolCreator;
typedef void *DataIterCreator;
typedef void *DataIterHandle;
typedef void *KVStoreHandle;
typedef void *RecordIOHandle;
typedef void *RtcHandle;
typedef void *OptimizerCreator;
typedef void *OptimizerHandle;
typedef uint32_t mx_uint;
typedef float mx_float;

/*! \brief Executor monitor callback: (output name, value, user handle).
 * Reference ExecutorMonitorCallback. */
typedef void (*ExecutorMonitorCallback)(const char *name, NDArrayHandle arr,
                                        void *handle);
/*! \brief KVStore server command controller (reference
 * MXKVStoreServerController). */
typedef void (*MXKVStoreServerController)(int head, const char *body,
                                          void *controller_handle);

/*! \brief C custom operator callbacks (reference CustomOpInfo /
 * CustomOpPropInfo / CustomOpPropCreator, include/mxnet/c_api.h:96-133).
 * ptrs are NDArrayHandles; tags: 0 = in_data, 1 = out_data, 2 = aux,
 * 3 = in_grad, 4 = out_grad. */
struct CustomOpInfo {
  int (*forward)(int size, void **ptrs, int *tags, const int *reqs,
                 int is_train, void *state);
  int (*backward)(int size, void **ptrs, int *tags, const int *reqs,
                  int is_train, void *state);
  int (*del)(void *state);
  void *p_forward;
  void *p_backward;
  void *p_del;
};

struct CustomOpPropInfo {
  int (*list_arguments)(char ***args, void *state);
  int (*list_outputs)(char ***outputs, void *state);
  int (*infer_shape)(int num_input, int *ndims, unsigned **shapes,
                     void *state);
  int (*create_operator)(const char *ctx, int num_inputs, unsigned **shapes,
                         int *ndims, int *dtypes, struct CustomOpInfo *ret,
                         void *state);
  int (*list_auxiliary_states)(char ***aux, void *state);
  int (*del)(void *state);
  void *p_list_arguments;
  void *p_list_outputs;
  void *p_infer_shape;
  void *p_create_operator;
  void *p_list_auxiliary_states;
  void *p_del;
};

typedef int (*CustomOpPropCreator)(const char *op_type, const int num_kwargs,
                                   const char **keys, const char **values,
                                   struct CustomOpPropInfo *ret);

/*! \brief KVStore updater: key, pushed value, stored value (mutate via
 * MXNDArraySyncCopyFromCPU), user handle. Reference MXKVStoreUpdater. */
typedef void (*MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                 NDArrayHandle local, void *handle);

const char *MXGetLastError(void);

/* ---- NDArray ---------------------------------------------------------- */

/*! \brief Create an f32 NDArray of the given shape (dev_type 1=cpu,
 * 2=tpu), zero-initialized. */
int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
/*! \brief Shape query; pointers valid until the next call on this
 * handle or Free. */
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_ndim,
                      const mx_uint **out_pdata);
/*! \brief Blocking host->device copy of `size` floats. */
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const mx_float *data,
                             mx_uint size);
/*! \brief Blocking device->host copy of `size` floats. */
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, mx_float *data,
                           mx_uint size);
int MXNDArrayWaitAll(void);
/*! \brief Save named arrays to the reference-compatible container. */
int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args, const char **keys);
/*! \brief Load a container; returns parallel arrays of handles and
 * names (valid until MXNDArrayListFree). */
int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names);
int MXNDArrayListFree(NDArrayHandle *arr, mx_uint size,
                      const char **names);
/*! \brief New caller-owned handle over the SAME underlying NDArray
 * object — an aliasing handle, not a copy: writes through either
 * handle (e.g. MXNDArraySyncCopyFromCPU) are visible through both.
 * Lets a frontend detach MXNDArrayLoad results from the load record
 * and release the record immediately (the loaded originals are freed
 * with the record, leaving the dup as sole owner). */
int MXNDArrayDup(NDArrayHandle handle, NDArrayHandle *out);
/*! \brief Create with explicit dtype (0=f32 1=f64 2=f16 3=u8 4=i32 5=i8
 * 6=i64 7=bf16 — the mshadow-compatible ids). */
int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int dtype, NDArrayHandle *out);
/*! \brief Axis-0 slice [start, stop) as a NEW array (jax arrays are
 * immutable, so unlike the reference this does not alias memory). */
int MXNDArraySlice(NDArrayHandle handle, mx_uint start, mx_uint stop,
                   NDArrayHandle *out);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int *dims,
                     NDArrayHandle *out);
int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id);
int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);
/*! \brief Wrap a CPython mxnet_tpu NDArray object (PyObject*) into a C
 * handle (increfs). Internal bridge for callback plumbing. */
int MXTPUNDArrayWrapPyObject(void *py_ndarray, NDArrayHandle *out);
/*! \brief Empty handle; filled by ops that allocate their output
 * (reference MXNDArrayCreateNone). */
int MXNDArrayCreateNone(NDArrayHandle *out);
/*! \brief Index axis 0: out = handle[idx] (rank reduced by one).
 * Divergence from the reference (NDArray::At returned a chunk-sharing
 * view): device arrays are immutable here, so the result is an
 * INDEPENDENT COPY — writes through it do not propagate back. */
int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle *out);
/*! \brief Host pointer to the array's f32 data. Divergence from the
 * reference (which returned the live CPU buffer): device arrays are
 * immutable here, so this is a cached host COPY, valid until the next
 * call on this handle or Free; writes do not propagate back. */
int MXNDArrayGetData(NDArrayHandle handle, mx_float **out_pdata);
/*! \brief Serialize one array to the container byte format (buffer owned
 * by the handle, valid until next call/Free). */
int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                          const char **out_buf);
int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                              NDArrayHandle *out);
int MXNDArrayWaitToRead(NDArrayHandle handle);
int MXNDArrayWaitToWrite(NDArrayHandle handle);
/*! \brief Seed the global PRNG (reference MXRandomSeed). */
int MXRandomSeed(int seed);
/*! \brief Drain the engine before process exit (reference
 * MXNotifyShutdown). */
int MXNotifyShutdown(void);

/* ---- NDArray function registry (reference c_api.cc:366-445) ----------- */

/*! \brief Enumerate registered imperative functions; handles are valid
 * for the process lifetime. */
int MXListFunctions(mx_uint *out_size, FunctionHandle **out_array);
int MXGetFunction(const char *name, FunctionHandle *out);
/*! \brief Name + doc + arity; strings valid for the process lifetime. */
int MXFuncGetInfo(FunctionHandle fun, const char **name,
                  const char **description, mx_uint *num_args,
                  const char ***arg_names, const char ***arg_type_infos,
                  const char ***arg_descriptions);
/*! \brief Arity contract: scalars follow the use vars (type_mask is
 * always 1, kNDArrayArgBeforeScalar). */
int MXFuncDescribe(FunctionHandle fun, mx_uint *num_use_vars,
                   mx_uint *num_scalars, mx_uint *num_mutate_vars,
                   int *type_mask);
/*! \brief result written into mutate_vars[0]. */
int MXFuncInvoke(FunctionHandle fun, NDArrayHandle *use_vars,
                 const mx_float *scalar_args, NDArrayHandle *mutate_vars);
/*! \brief MXFuncInvoke with extra string kwargs (reference
 * MXFuncInvokeEx). */
int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle *use_vars,
                   const mx_float *scalar_args, NDArrayHandle *mutate_vars,
                   int num_params, const char **param_keys,
                   const char **param_vals);
/*! \brief Register a C custom operator usable as sym.Custom(...,
 * op_type=<op_type>) from every frontend (reference MXCustomOpRegister). */
int MXCustomOpRegister(const char *op_type, CustomOpPropCreator creator);

/* ---- Symbol ----------------------------------------------------------- */

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
/*! \brief Serialize; the returned string is valid until the next call
 * on this handle or Free. */
int MXSymbolSaveToJSON(SymbolHandle handle, const char **out_json);
/*! \brief List argument names; valid until next call/Free. */
int MXSymbolListArguments(SymbolHandle handle, mx_uint *out_size,
                          const char ***out_array);
int MXSymbolListOutputs(SymbolHandle handle, mx_uint *out_size,
                        const char ***out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle handle, mx_uint *out_size,
                                const char ***out_array);
/*! \brief Shape inference from named input shapes. Returns per-argument
 * shapes (csr layout: ind[i]..ind[i+1] into data). Buffers valid until
 * next call/Free. */
int MXSymbolInferShape(SymbolHandle handle, mx_uint num_args,
                       const char **keys, const mx_uint *arg_ind_ptr,
                       const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data);
int MXSymbolFree(SymbolHandle handle);

/* ---- Symbol registry + composition (reference c_api.cc:447-937) ------- */

/*! \brief Enumerate registered operators. */
int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array);
int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **out_name);
/*! \brief Op metadata: doc + declared params (name/type/doc triplets);
 * key_var_num_args names the variadic-arity param ("num_args" for
 * Concat-likes, "" otherwise). Strings valid for the process lifetime. */
int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char **name, const char **description,
                                mx_uint *num_args, const char ***arg_names,
                                const char ***arg_type_infos,
                                const char ***arg_descriptions,
                                const char **key_var_num_args);
/*! \brief Create an un-composed op application from string params. */
int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator, mx_uint num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out);
/*! \brief Supply inputs to an atomic symbol (keys NULL = positional);
 * the handle becomes a composed Symbol in place. */
int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args);
int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle *symbols,
                        SymbolHandle *out);
int MXSymbolCopy(SymbolHandle symbol, SymbolHandle *out);
int MXSymbolGetInternals(SymbolHandle symbol, SymbolHandle *out);
int MXSymbolGetOutput(SymbolHandle symbol, mx_uint index, SymbolHandle *out);
/*! \brief Attribute access on a single-output symbol; *out is "" and
 * *success 0 when unset. */
int MXSymbolGetAttr(SymbolHandle symbol, const char *key, const char **out,
                    int *success);
int MXSymbolSetAttr(SymbolHandle symbol, const char *key, const char *value);
/*! \brief Flattened [k0,v0,k1,v1,...] with keys as <node>__<attr>. */
int MXSymbolListAttr(SymbolHandle symbol, mx_uint *out_size,
                     const char ***out);
/*! \brief Dtype inference from named dtype ids (see MXNDArrayCreateEx);
 * id arrays valid until the next call on this handle or Free. */
int MXSymbolInferType(SymbolHandle handle, mx_uint num_args,
                      const char **keys, const int *arg_type_data,
                      mx_uint *in_type_size, const int **in_type_data,
                      mx_uint *out_type_size, const int **out_type_data,
                      mx_uint *aux_type_size, const int **aux_type_data);
int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out);
int MXSymbolSaveToFile(SymbolHandle symbol, const char *fname);
/*! \brief Name of a single-output symbol; *success 0 for groups. */
int MXSymbolGetName(SymbolHandle symbol, const char **out, int *success);
/*! \brief Human-readable graph dump (reference Symbol::Print). */
int MXSymbolPrint(SymbolHandle symbol, const char **out_str);
/*! \brief Gradient symbol wrt the named arguments (reference
 * MXSymbolGrad / Symbol::Grad). */
int MXSymbolGrad(SymbolHandle sym, mx_uint num_wrt, const char **wrt,
                 SymbolHandle *out);
/*! \brief Shape inference that tolerates unknowns: unknown entries come
 * back 0-dim; *complete is 1 when everything resolved (reference
 * MXSymbolInferShapePartial). Also returns aux shapes. */
int MXSymbolInferShapePartial(SymbolHandle handle, mx_uint num_args,
                              const char **keys, const mx_uint *arg_ind_ptr,
                              const mx_uint *arg_shape_data,
                              mx_uint *in_shape_size,
                              const mx_uint **in_shape_ndim,
                              const mx_uint ***in_shape_data,
                              mx_uint *out_shape_size,
                              const mx_uint **out_shape_ndim,
                              const mx_uint ***out_shape_data,
                              mx_uint *aux_shape_size,
                              const mx_uint **aux_shape_ndim,
                              const mx_uint ***aux_shape_data,
                              int *complete);
/*! \brief Attributes of the symbol's own node only, flattened
 * [k0,v0,...] (reference MXSymbolListAttrShallow). */
int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint *out_size,
                            const char ***out);

/* ---- Data iterators (reference c_api.cc:1110-1197) -------------------- */

int MXListDataIters(mx_uint *out_size, DataIterCreator **out_array);
int MXDataIterGetIterInfo(DataIterCreator creator, const char **name,
                          const char **description);
/*! \brief Create from string kwargs (values parsed as python literals
 * where possible: ints, floats, tuples, bools; else kept as strings). */
int MXDataIterCreateIter(DataIterCreator creator, mx_uint num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
/*! \brief Advance; *out = 1 while data remains. */
int MXDataIterNext(DataIterHandle handle, int *out);
/*! \brief Current batch data/label. The returned handle is owned by the
 * iterator (do NOT free); valid until the next Next/Free. */
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetPadNum(DataIterHandle handle, int *pad);
/*! \brief Instance indices of the current batch (uint64). *out_size 0
 * when the iterator does not track indices. */
int MXDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                       uint64_t *out_size);
int MXDataIterFree(DataIterHandle handle);

/* ---- KVStore (reference c_api.cc:1199-1338) --------------------------- */

int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
/*! \brief Install a C updater run on every push (server-side optimizer
 * equivalent). */
int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle);
int MXKVStoreGetType(KVStoreHandle handle, const char **type);
int MXKVStoreGetRank(KVStoreHandle handle, int *rank);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size);
int MXKVStoreBarrier(KVStoreHandle handle);
int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle, int do_barrier);
int MXKVStoreGetNumDeadNode(KVStoreHandle handle, int node_id, int *number);
int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_head,
                                   const char *cmd_body);
/*! \brief Set process-role environment (DMLC_ROLE etc.) before creating
 * a dist kvstore (reference MXInitPSEnv). */
int MXInitPSEnv(mx_uint num_vars, const char **keys, const char **vals);
/*! \brief Role predicates from DMLC_ROLE (default: worker). The TPU
 * dist design has no separate server/scheduler processes — every rank
 * is a worker over XLA collectives — so IsServerNode/IsSchedulerNode
 * return 0 unless the env says otherwise (docs/distributed.md). */
int MXKVStoreIsWorkerNode(int *ret);
int MXKVStoreIsServerNode(int *ret);
int MXKVStoreIsSchedulerNode(int *ret);
/*! \brief Install `controller` as the command handler and return.
 * Divergence from the reference (which blocked a dedicated server
 * process): there is no server tier here, so commands sent with
 * MXKVStoreSendCommmandToServers dispatch to the controller in-process. */
int MXKVStoreRunServer(KVStoreHandle handle,
                       MXKVStoreServerController controller,
                       void *controller_handle);

/* ---- RecordIO (reference MXRecordIO*) --------------------------------- */

int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOWriterFree(RecordIOHandle handle);
int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                size_t size);
int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOReaderFree(RecordIOHandle handle);
/*! \brief Read the next record; *size 0 at end of file. Buffer owned by
 * the handle, valid until the next read/Free. */
int MXRecordIOReaderReadRecord(RecordIOHandle handle, const char **buf,
                               size_t *size);
/*! \brief Seek to a byte offset previously returned by Tell (pointer-to-
 * handle signature kept for reference parity). */
int MXRecordIOReaderSeek(RecordIOHandle *handle, size_t pos);
/*! \brief Current byte offset of the writer (pair with ReaderSeek for
 * indexed access). */
int MXRecordIOWriterTell(RecordIOHandle *handle, size_t *pos);

/* ---- Optimizer (reference MXOptimizer*) ------------------------------- */

/*! \brief Look up a registered optimizer by name ("sgd", "adam", ...). */
int MXOptimizerFindCreator(const char *key, OptimizerCreator *out);
/*! \brief Instantiate with string kwargs (momentum, rescale_grad, ...). */
int MXOptimizerCreateOptimizer(OptimizerCreator creator, mx_uint num_param,
                               const char **keys, const char **vals,
                               OptimizerHandle *out);
int MXOptimizerFree(OptimizerHandle handle);
/*! \brief In-place weight update with per-call lr/wd; per-index state
 * (momentum etc.) lives on the handle. */
int MXOptimizerUpdate(OptimizerHandle handle, int index, NDArrayHandle weight,
                      NDArrayHandle grad, mx_float lr, mx_float wd);

/* ---- Rtc: runtime-compiled Pallas kernels (reference MXRtc*) ---------- */

/*! \brief Compile a named Pallas kernel (see mxnet_tpu.rtc.Rtc): body
 * sees <name>_ref refs for each input/output. */
int MXRtcCreate(const char *name, mx_uint num_input, mx_uint num_output,
                const char **input_names, const char **output_names,
                NDArrayHandle *inputs, NDArrayHandle *outputs,
                const char *kernel, RtcHandle *out);
/*! \brief Run on new arrays; grid/block dims accepted for reference API
 * parity (Pallas/XLA choose the schedule). */
int MXRtcPush(RtcHandle handle, mx_uint num_input, mx_uint num_output,
              NDArrayHandle *inputs, NDArrayHandle *outputs,
              mx_uint gridDimX, mx_uint gridDimY, mx_uint gridDimZ,
              mx_uint blockDimX, mx_uint blockDimY, mx_uint blockDimZ);
int MXRtcFree(RtcHandle handle);

/* ---- Executor --------------------------------------------------------- */

/*! \brief simple_bind: infer shapes from named inputs, allocate
 * args/grads/aux, bind (grad_req "write" when for_training != 0). */
int MXExecutorSimpleBind(SymbolHandle symbol, int dev_type, int dev_id,
                         mx_uint num_args, const char **keys,
                         const mx_uint *arg_ind_ptr,
                         const mx_uint *arg_shape_data, int for_training,
                         ExecutorHandle *out);
/*! \brief Copy data into a named argument (input or parameter). */
int MXExecutorSetArg(ExecutorHandle handle, const char *name,
                     const mx_float *data, mx_uint size);
/*! \brief Copy data into a named auxiliary state (e.g. BatchNorm moving
 * stats restored from a checkpoint's aux: entries). */
int MXExecutorSetAux(ExecutorHandle handle, const char *name,
                     const mx_float *data, mx_uint size);
/*! \brief Copy auxiliary state `name` to host (`size` floats). */
int MXExecutorGetAux(ExecutorHandle handle, const char *name,
                     mx_float *data, mx_uint size);
int MXExecutorForward(ExecutorHandle handle, int is_train);
/*! \brief Backward with implicit all-ones head gradients. */
int MXExecutorBackward(ExecutorHandle handle);
/*! \brief Number of outputs. */
int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size);
/*! \brief Copy output `index` to host (`size` floats must match). */
int MXExecutorGetOutput(ExecutorHandle handle, mx_uint index,
                        mx_float *data, mx_uint size);
/*! \brief Copy the gradient of argument `name` to host. */
int MXExecutorGetGrad(ExecutorHandle handle, const char *name,
                      mx_float *data, mx_uint size);
int MXExecutorFree(ExecutorHandle handle);
/*! \brief Full bind with caller-provided argument/gradient/aux arrays
 * (reference MXExecutorBind). grad_req_type: 0=null 1=write 2=inplace
 * 3=addto; arg_grad_store entries may be NULL for unneeded grads.
 * Results are written back into the passed NDArray handles after each
 * forward/backward. */
int MXExecutorBind(SymbolHandle symbol_handle, int dev_type, int dev_id,
                   mx_uint len, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                   mx_uint aux_states_len, NDArrayHandle *aux_states,
                   ExecutorHandle *out);
/*! \brief Bind with a group->context map (reference MXExecutorBindX):
 * map keys are ctx_group attr values, mapped to (dev_type, dev_id). */
int MXExecutorBindX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                    mx_uint num_map_keys, const char **map_keys,
                    const int *map_dev_types, const int *map_dev_ids,
                    mx_uint len, NDArrayHandle *in_args,
                    NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                    mx_uint aux_states_len, NDArrayHandle *aux_states,
                    ExecutorHandle *out);
/*! \brief BindX plus a shared executor whose memory pool is reused
 * (reference MXExecutorBindEX; here XLA owns buffers, so shared_exec
 * only seeds bucketing-style shape reuse and may be NULL). */
int MXExecutorBindEX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                     mx_uint num_map_keys, const char **map_keys,
                     const int *map_dev_types, const int *map_dev_ids,
                     mx_uint len, NDArrayHandle *in_args,
                     NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                     mx_uint aux_states_len, NDArrayHandle *aux_states,
                     ExecutorHandle shared_exec, ExecutorHandle *out);
/*! \brief Allocation/graph dump (reference GraphExecutor::Print). */
int MXExecutorPrint(ExecutorHandle handle, const char **out_str);
/*! \brief Install a per-output monitor callback run once per training
 * batch (reference MXExecutorSetMonitorCallback). Ownership of the
 * NDArrayHandle passed to the callback transfers to the callee, which
 * must release it with MXNDArrayFree (reference convention:
 * graph_executor.cc hands the frontend a freshly allocated NDArray). */
int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void *callback_handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_API_H_ */
