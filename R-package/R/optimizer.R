# R-side optimizers (reference R-package/R/optimizer.R): mx.opt.sgd
# returns an updater closure carrying per-weight momentum state; the
# lr schedule is consulted per update. (The C-backed per-handle
# optimizer surface, mx.opt.create/mx.opt.update, lives in mxnet.R.)

mx.opt.sgd <- function(learning.rate = 0.01, momentum = 0,
                       wd = 0, clip_gradient = NULL,
                       lr_scheduler = NULL, rescale.grad = 1) {
  state <- new.env(parent = emptyenv())
  state$mom <- list()
  state$num.update <- 0
  function(name, weight, grad) {
    state$num.update <- state$num.update + 1
    lr <- if (is.null(lr_scheduler)) learning.rate
          else lr_scheduler(learning.rate, state$num.update)
    g <- grad * rescale.grad
    if (!is.null(clip_gradient))
      g <- pmin(pmax(g, -clip_gradient), clip_gradient)
    g <- g + wd * weight
    if (momentum > 0) {
      m <- state$mom[[name]]
      if (is.null(m)) m <- array(0, dim = dim(weight))
      m <- momentum * m - lr * g
      state$mom[[name]] <- m
      weight + m
    } else {
      weight - lr * g
    }
  }
}

mx.opt.create.updater <- function(optimizer = "sgd", ...) {
  switch(optimizer,
         sgd = mx.opt.sgd(...),
         stop("mx.opt.create.updater: unknown optimizer ", optimizer))
}
