# Training callbacks (reference R-package/R/callback.R): epoch/batch
# callbacks receive (iteration, nbatch, env) where env carries the
# metric state; returning FALSE from an epoch callback stops training.

mx.callback.log.train.metric <- function(period, logger = NULL) {
  function(iteration, nbatch, env) {
    if (nbatch %% period == 0 && !is.null(env$metric)) {
      res <- env$metric$get(env$train.metric.state)
      cat(sprintf("Batch [%d] Train-%s=%f\n", nbatch, res$name, res$value))
      if (!is.null(logger)) logger(iteration, nbatch, res)
    }
    TRUE
  }
}

mx.callback.save.checkpoint <- function(prefix, period = 1) {
  function(iteration, nbatch, env) {
    if (iteration %% period == 0 && !is.null(env$model)) {
      mx.model.save(env$model, prefix, iteration)
      cat(sprintf("Model checkpoint saved to %s-%04d.params\n",
                  prefix, iteration))
    }
    TRUE
  }
}
