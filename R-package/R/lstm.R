# LSTM training / inference API (reference R-package/R/lstm.R:1-361).
# The reference builds the cell by hand (i2h/h2h FullyConnected +
# SliceChannel into 4 gates per timestep, lstm.R:1-28) and unrolls it
# seq.len times; here the recurrence is the framework's fused scan-based
# `RNN` symbol (see rnn_model.R) — same model family, one
# seq.len-independent graph. The public entry points and their argument
# names match the reference's.

#' Train an LSTM language-model on (seq.len, nsample) token arrays.
#' train.data / eval.data: list(data=, label=) of integer id arrays.
#' (reference mx.lstm, lstm.R:152-241)
mx.lstm <- function(train.data, eval.data = NULL,
                    num.lstm.layer, seq.len,
                    num.hidden, num.embed, num.label,
                    batch.size, input.size,
                    ctx = mx.cpu(),
                    num.round = 10, update.period = 1,
                    initializer = mx.init.uniform(0.01),
                    dropout = 0, optimizer = "sgd", ...) {
  mx.rnn.create("lstm", train.data, eval.data,
                num.rnn.layer = num.lstm.layer, seq.len = seq.len,
                num.hidden = num.hidden, num.embed = num.embed,
                num.label = num.label, batch.size = batch.size,
                input.size = input.size, ctx = ctx,
                num.round = num.round, update.period = update.period,
                initializer = initializer, dropout = dropout,
                optimizer = optimizer, ...)
}

#' Single-step LSTM inference model carrying h/c state across calls
#' (reference mx.lstm.inference, lstm.R:244-320)
mx.lstm.inference <- function(num.lstm.layer, input.size, num.hidden,
                              num.embed, num.label, batch.size = 1,
                              arg.params, ctx = mx.cpu(), dropout = 0) {
  mx.rnn.infer.model("lstm", num.rnn.layer = num.lstm.layer,
                   input.size = input.size, num.hidden = num.hidden,
                   num.embed = num.embed, num.label = num.label,
                   batch.size = batch.size, arg.params = arg.params,
                   ctx = ctx, dropout = dropout)
}

#' One forward step of an LSTM inference model; new.seq=TRUE resets the
#' carried state (reference mx.lstm.forward, lstm.R:322-361)
mx.lstm.forward <- function(model, input.data, new.seq = FALSE) {
  mx.rnn.step(model, input.data, new.seq)
}
