# Vanilla-RNN training / inference API (reference R-package/R/rnn.R:1-342;
# reference cell is one i2h+h2h FullyConnected with tanh/relu,
# rnn.R:1-26 — here the fused scan-based `RNN` symbol, see rnn_model.R).
# Entry points and argument names match the reference.

#' Train a vanilla-RNN language-model; active.func "tanh" or "relu"
#' (reference mx.rnn, rnn.R:136-226)
mx.rnn <- function(train.data, eval.data = NULL,
                   num.rnn.layer, seq.len,
                   num.hidden, num.embed, num.label,
                   batch.size, input.size,
                   active.func = "tanh",
                   ctx = mx.cpu(),
                   num.round = 10, update.period = 1,
                   initializer = mx.init.uniform(0.01),
                   dropout = 0, optimizer = "sgd", ...) {
  if (!active.func %in% c("tanh", "relu"))
    stop("mx.rnn: active.func must be 'tanh' or 'relu'")
  mx.rnn.create(paste0("rnn_", active.func), train.data, eval.data,
                num.rnn.layer = num.rnn.layer, seq.len = seq.len,
                num.hidden = num.hidden, num.embed = num.embed,
                num.label = num.label, batch.size = batch.size,
                input.size = input.size, ctx = ctx,
                num.round = num.round, update.period = update.period,
                initializer = initializer, dropout = dropout,
                optimizer = optimizer, ...)
}

#' Single-step vanilla-RNN inference model (reference mx.rnn.inference,
#' rnn.R:229-303)
mx.rnn.inference <- function(num.rnn.layer, input.size, num.hidden,
                             num.embed, num.label, batch.size = 1,
                             arg.params, active.func = "tanh",
                             ctx = mx.cpu(), dropout = 0) {
  if (!active.func %in% c("tanh", "relu"))
    stop("mx.rnn.inference: active.func must be 'tanh' or 'relu'")
  mx.rnn.infer.model(paste0("rnn_", active.func),
                     num.rnn.layer = num.rnn.layer,
                     input.size = input.size, num.hidden = num.hidden,
                     num.embed = num.embed, num.label = num.label,
                     batch.size = batch.size, arg.params = arg.params,
                     ctx = ctx, dropout = dropout)
}

#' One forward step of a vanilla-RNN inference model (reference
#' mx.rnn.forward, rnn.R:305-342)
mx.rnn.forward <- function(model, input.data, new.seq = FALSE) {
  mx.rnn.step(model, input.data, new.seq)
}
