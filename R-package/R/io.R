# Array data iterator (reference R-package/R/io.R mx.io.arrayiter +
# model.R mx.model.init.iter): batches an in-memory dataset with
# shuffling and wrap-around padding of the last batch. The package's
# internal layout is colmajor — X dim = (feature..., nsample), batch
# axis LAST — so a batch slice is contiguous in R.

mx.io.arrayiter <- function(data, label = NULL, batch.size = 128,
                            shuffle = FALSE) {
  data <- as.array(data)
  if (is.null(dim(data))) dim(data) <- length(data)
  ndim <- length(dim(data))
  n <- dim(data)[[ndim]]
  env <- new.env(parent = emptyenv())
  env$order <- seq_len(n)
  env$cursor <- 0L

  take <- function(x, idx) {
    if (is.null(x)) return(NULL)
    if (is.null(dim(x)) || length(dim(x)) == 1) return(x[idx])
    # index the last (sample) axis, keeping the rest
    do.call(`[`, c(list(x), rep(list(quote(expr = )), length(dim(x)) - 1),
                   list(idx), drop = FALSE))
  }

  list(
    batch.size = batch.size,
    num.data = n,
    reset = function() {
      env$cursor <- 0L
      if (shuffle) env$order <- sample(n)
      invisible(NULL)
    },
    iter.next = function() {
      env$cursor <- env$cursor + batch.size
      env$cursor - batch.size < n
    },
    value = function() {
      lo <- env$cursor - batch.size + 1L
      idx <- lo:(lo + batch.size - 1L)
      pad <- sum(idx > n)
      idx[idx > n] <- idx[idx > n] - n    # wrap-around pad
      list(data = take(data, env$order[idx]),
           label = take(label, env$order[idx]),
           pad = pad)
    }
  )
}
