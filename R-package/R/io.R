# Array data iterator (reference R-package/R/io.R mx.io.arrayiter +
# model.R mx.model.init.iter): batches an in-memory dataset with
# shuffling and wrap-around padding of the last batch. The package's
# internal layout is colmajor — X dim = (feature..., nsample), batch
# axis LAST — so a batch slice is contiguous in R.

mx.io.arrayiter <- function(data, label = NULL, batch.size = 128,
                            shuffle = FALSE) {
  data <- as.array(data)
  if (is.null(dim(data))) dim(data) <- length(data)
  ndim <- length(dim(data))
  n <- dim(data)[[ndim]]
  env <- new.env(parent = emptyenv())
  env$order <- seq_len(n)
  env$cursor <- 0L

  take <- function(x, idx) {
    if (is.null(x)) return(NULL)
    if (is.null(dim(x)) || length(dim(x)) == 1) return(x[idx])
    # index the last (sample) axis, keeping the rest
    do.call(`[`, c(list(x), rep(list(quote(expr = )), length(dim(x)) - 1),
                   list(idx), drop = FALSE))
  }

  list(
    batch.size = batch.size,
    num.data = n,
    reset = function() {
      env$cursor <- 0L
      if (shuffle) env$order <- sample(n)
      invisible(NULL)
    },
    iter.next = function() {
      env$cursor <- env$cursor + batch.size
      env$cursor - batch.size < n
    },
    value = function() {
      lo <- env$cursor - batch.size + 1L
      idx <- lo:(lo + batch.size - 1L)
      pad <- sum(idx > n)
      idx[idx > n] <- idx[idx > n] - n    # wrap-around pad
      list(data = take(data, env$order[idx]),
           label = take(label, env$order[idx]),
           pad = pad)
    }
  )
}

# ---- runtime-backed iterators ----------------------------------------------
# Parity target: the reference's generated io creators
# (R-package/R/mxnet_generated.R:480-610): mx.io.ImageRecordIter,
# mx.io.MNISTIter, mx.io.CSVIter. Each rides the runtime's iterator
# registry through .Call glue (src/mxnet_glue.c mxr_io_*) and returns the
# same contract as mx.io.arrayiter: list(batch.size, reset, iter.next,
# value), with value()$data in R column-major layout (sample axis LAST).

mx.io.create <- function(name, ...) {
  kw <- list(...)
  kw <- Filter(Negate(is.null), kw)   # NULL kwarg == omitted (R idiom)
  if (length(kw) && (is.null(names(kw)) || any(names(kw) == "")))
    stop("mx.io.create: all iterator parameters must be named")
  # R convention uses dots in argument names; the runtime expects
  # underscores (batch.size -> batch_size), like the reference R package
  keys <- gsub("\\.", "_", names(kw))
  # shape-typed keys need tuple syntax even for one dimension
  # (data.shape = 1 -> "(1,)"): .mx.param.str is the one shared
  # value serializer for the ABI
  vals <- vapply(seq_along(kw), function(i) {
    .mx.param.str(kw[[i]], force.tuple = grepl("shape$", keys[[i]]))
  }, character(1))
  handle <- .Call(mxr_io_create, name, keys, unname(vals))

  to.r <- function(values) {
    cdim <- attr(values, "mx.dim")
    if (length(cdim) <= 1) return(as.numeric(values))
    .mx.from.c.order(values, rev(cdim))
  }
  bs <- kw[["batch.size"]]
  if (is.null(bs)) bs <- kw[["batch_size"]]

  list(
    batch.size = if (is.null(bs)) NA_integer_ else as.integer(bs),
    reset = function() {
      .Call(mxr_io_before_first, handle)
      invisible(NULL)
    },
    iter.next = function() .Call(mxr_io_next, handle) != 0L,
    value = function() {
      v <- .Call(mxr_io_value, handle)
      list(data = to.r(v$data), label = to.r(v$label),
           pad = v$pad)
    }
  )
}

mx.io.ImageRecordIter <- function(...) mx.io.create("ImageRecordIter", ...)
mx.io.MNISTIter <- function(...) mx.io.create("MNISTIter", ...)
mx.io.CSVIter <- function(...) mx.io.create("CSVIter", ...)
