# Computation-graph visualization (reference R-package/R/viz.graph.R:1-158,
# mx.model.graph.viz over DiagrammeR). This redesign emits standard
# Graphviz DOT text from the symbol's JSON — renderable by any dot
# binary or viewer, with no hard package dependency; if DiagrammeR is
# installed the DOT is rendered inline like the reference did.

# node shapes/fills by operator family (reference viz.graph.R:60-101
# used the same grouping for its node styling)
.mx.viz.node.style <- function(op, param) {
  if (identical(op, "null"))
    return(c(shape = "ellipse", fill = "#8dd3c7"))
  label.extra <- ""
  if (op %in% c("Convolution", "Deconvolution"))
    label.extra <- paste0("\\n", param$kernel, "/", param$num_filter)
  if (identical(op, "FullyConnected"))
    label.extra <- paste0("\\n", param$num_hidden)
  if (identical(op, "Activation") || identical(op, "LeakyReLU"))
    label.extra <- paste0("\\n", param$act_type)
  if (identical(op, "Pooling"))
    label.extra <- paste0("\\n", param$pool_type, " ", param$kernel)
  if (identical(op, "RNN"))
    label.extra <- paste0("\\n", param$mode, " x", param$num_layers)
  fill <- switch(op,
    Convolution = , Deconvolution = , FullyConnected = "#fb8072",
    Activation = , LeakyReLU = "#ffffb3",
    Pooling = "#80b1d3",
    BatchNorm = "#bebada",
    SoftmaxOutput = , LinearRegressionOutput = ,
    LogisticRegressionOutput = , MAERegressionOutput = "#fccde5",
    RNN = "#b3de69",
    "#d9d9d9")
  c(shape = "box", fill = fill, extra = label.extra)
}

#' Render a symbol's computation graph as Graphviz DOT text.
#'
#' @param symbol MXSymbol to draw
#' @param graph.title character title
#' @param render logical: if TRUE and DiagrammeR is installed, render
#'   the DOT (reference behavior); the DOT string is always returned
#'   invisibly so it can be written to a .dot/.gv file.
#' @return the DOT source, invisibly
#' (reference graph.viz, viz.graph.R:24-158)
graph.viz <- function(symbol, graph.title = "Computation Graph",
                      render = TRUE) {
  if (!requireNamespace("jsonlite", quietly = TRUE))
    stop("graph.viz needs the jsonlite package to parse symbol JSON")
  g <- jsonlite::fromJSON(tojson.MXSymbol(symbol),
                          simplifyDataFrame = FALSE)
  nodes <- g$nodes
  lines <- c("digraph mxnet_tpu {",
             sprintf("  label=\"%s\"; labelloc=top; rankdir=BT;",
                     graph.title),
             "  node [fontsize=10, style=filled];")
  # hide weight/bias/state leaves like the reference (viz.graph.R:49-58
  # drops *_weight/*_bias/*_label auxiliaries from the drawing)
  hidden <- vapply(seq_along(nodes), function(i) {
    n <- nodes[[i]]
    identical(n$op, "null") &&
      grepl("(weight|bias|gamma|beta|label|state|parameters)$", n$name)
  }, logical(1))
  for (i in seq_along(nodes)) {
    if (hidden[[i]]) next
    n <- nodes[[i]]
    st <- .mx.viz.node.style(n$op, n$param)
    label <- if (identical(n$op, "null")) n$name
             else paste0(n$op, if (!is.null(st[["extra"]])) st[["extra"]]
                               else "", "\\n", n$name)
    lines <- c(lines, sprintf(
      "  n%d [label=\"%s\", shape=%s, fillcolor=\"%s\"];",
      i, label, st[["shape"]], st[["fill"]]))
  }
  for (i in seq_along(nodes)) {
    if (hidden[[i]]) next
    for (inp in nodes[[i]]$inputs) {
      src <- inp[[1]] + 1L                # JSON ids are 0-based
      if (hidden[[src]]) next
      lines <- c(lines, sprintf("  n%d -> n%d;", src, i))
    }
  }
  lines <- c(lines, "}")
  dot <- paste(lines, collapse = "\n")
  if (render && requireNamespace("DiagrammeR", quietly = TRUE))
    print(DiagrammeR::grViz(dot))
  invisible(dot)
}

#' Reference-compatible alias (the reference exported the same drawing
#' under mx.model.graph.viz in later revisions)
mx.graph.viz <- graph.viz
