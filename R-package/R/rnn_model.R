# Recurrent model tier (reference R-package/R/rnn_model.R:1-243 plus the
# shared halves of lstm.R/gru.R/rnn.R): setup + training + inference
# machinery behind mx.lstm / mx.gru / mx.rnn.
#
# TPU-native redesign: the reference unrolls seq.len copies of a cell
# graph in R (lstm.R:31-90, one SliceChannel slice + 2 FCs per step) and
# zeroes/copies states around every batch. Here the whole recurrence is
# ONE `RNN` symbol (the framework's lax.scan-backed fused op,
# mxnet_tpu/ops/seq.py:138) — the graph is seq.len-independent, compiles
# once, and runs the recurrence on-device. Public API names and
# arguments stay reference-compatible.

# weights get optimizer updates; data/label/states do not
# (reference rnn_model.R:1-4 is.param.name, extended with the fused
# RNN op's flat "parameters" vector)
mx.rnn.is.param.name <- function(name) {
  grepl("weight$", name) || grepl("bias$", name) ||
    grepl("gamma$", name) || grepl("beta$", name) ||
    grepl("parameters$", name)
}

# unrolled-equivalent training symbol: token ids -> embedding ->
# fused RNN -> per-step softmax over the vocabulary.
# R-side data layout is (seq.len, batch) colmajor, which crosses the
# ABI as C-order (batch, seq.len) — same convention as the reference.
mx.rnn.train.symbol <- function(mode, num.rnn.layer, num.hidden,
                                num.embed, num.label, input.size,
                                dropout = 0) {
  data <- mx.symbol.Variable("data")
  label <- mx.symbol.Variable("label")
  embed <- mx.symbol.create("Embedding", data = data,
                            input_dim = input.size,
                            output_dim = num.embed, name = "embed")
  # (batch, seq, embed) -> time-major (seq, batch, embed): the scan
  # axis must be leading for the fused op
  tm <- mx.symbol.create("transpose", embed, axes = c(1, 0, 2))
  rnn <- mx.symbol.create("RNN", tm, state_size = num.hidden,
                          num_layers = num.rnn.layer, mode = mode,
                          p = dropout, name = "rnn")
  flat <- mx.symbol.create("Reshape", rnn, shape = c(-1, num.hidden))
  fc <- mx.symbol.create("FullyConnected", flat, num_hidden = num.label,
                         name = "cls")
  # label (batch, seq) -> seq-major flat, matching the reshape order of
  # the time-major RNN output (reference lstm.R:84-86 transposes the
  # same way before its Reshape)
  lab <- mx.symbol.create("Reshape",
                          mx.symbol.create("transpose", label,
                                           axes = c(1, 0)),
                          shape = c(-1))
  mx.symbol.create("SoftmaxOutput", data = fc, label = lab, name = "sm")
}

# single-step inference symbol: one token in, next-token probs +
# carried states out (reference lstm.inference.symbol, lstm.R:92-149,
# which BlockGrads every state into the output group)
mx.rnn.inference.symbol <- function(mode, num.rnn.layer, num.hidden,
                                    num.embed, num.label, input.size,
                                    dropout = 0) {
  data <- mx.symbol.Variable("data")
  embed <- mx.symbol.create("Embedding", data = data,
                            input_dim = input.size,
                            output_dim = num.embed, name = "embed")
  tm <- mx.symbol.create("transpose", embed, axes = c(1, 0, 2))
  rnn <- mx.symbol.create("RNN", tm, state_size = num.hidden,
                          num_layers = num.rnn.layer, mode = mode,
                          p = dropout, state_outputs = TRUE,
                          name = "rnn")
  flat <- mx.symbol.create("Reshape", rnn[[1]],
                           shape = c(-1, num.hidden))
  fc <- mx.symbol.create("FullyConnected", flat, num_hidden = num.label,
                         name = "cls")
  sm <- mx.symbol.create("SoftmaxOutput", data = fc, name = "sm")
  outs <- list(sm)
  for (i in 2:length(outputs.MXSymbol(rnn)))
    outs[[i]] <- mx.symbol.create("BlockGrad", rnn[[i]])
  mx.symbol.Group(outs)
}

mx.rnn.state.names <- function(mode) {
  if (identical(mode, "lstm")) c("rnn_state", "rnn_state_cell")
  else "rnn_state"
}

# bind + init (reference setup.rnn.model, rnn_model.R:36-80)
mx.rnn.setup.model <- function(rnn.sym, mode, ctx, num.rnn.layer,
                               seq.len, num.hidden, num.embed,
                               num.label, batch.size, input.size,
                               initializer = mx.init.uniform(0.01)) {
  data.shape <- if (seq.len == 1) c(1, batch.size)
                else c(seq.len, batch.size)
  shape.args <- list(data = data.shape)
  arg.names <- arguments.MXSymbol(rnn.sym)
  if ("label" %in% arg.names) shape.args$label <- data.shape
  for (nm in mx.rnn.state.names(mode))
    shape.args[[nm]] <- c(num.hidden, batch.size, num.rnn.layer)

  shapes <- do.call(mx.symbol.infer.shape,
                    c(list(rnn.sym), shape.args))
  if (is.null(shapes))
    stop("mx.rnn.setup.model: cannot infer shapes")

  arg.params <- list()
  for (i in seq_along(arg.names)) {
    nm <- arg.names[[i]]
    if (mx.rnn.is.param.name(nm))
      arg.params[[nm]] <- initializer(nm, shapes$arg.shapes[[i]])
  }

  exec.args <- c(list(symbol = rnn.sym, ctx = ctx, grad.req = "write"),
                 shape.args)
  executor <- do.call(mx.simple.bind, exec.args)
  for (nm in names(arg.params))
    mx.exec.set.arg(executor, nm, arg.params[[nm]])
  # states start (and are re-zeroed per batch) at zero
  for (nm in mx.rnn.state.names(mode))
    mx.exec.set.arg(executor, nm,
                    array(0, dim = c(num.hidden, batch.size,
                                     num.rnn.layer)))

  list(rnn.exec = executor, symbol = rnn.sym, mode = mode,
       arg.params = arg.params, shapes = shapes, arg.names = arg.names,
       num.rnn.layer = num.rnn.layer, num.hidden = num.hidden,
       seq.len = seq.len, batch.size = batch.size,
       num.embed = num.embed, num.label = num.label,
       input.size = input.size)
}

# list(data=, label=) of (seq.len, nsample) integer arrays -> batch
# iterator (reference check.data + mx.model.init.iter.rnn,
# rnn_model.R:17-34 / 228-243)
mx.rnn.check.data <- function(data, batch.size, is.train) {
  if (is.null(data)) return(NULL)
  if (!is.list(data) || is.null(data$data) || is.null(data$label))
    stop("dataset must be list(data = array, label = array) of ",
         "(seq.len, nsample) token ids")
  X <- data$data
  y <- data$label
  if (is.null(dim(X)) || length(dim(X)) != 2)
    stop("rnn data must be a (seq.len, nsample) matrix of token ids")
  nsample <- ncol(X)
  if (nsample < batch.size)
    stop("need at least batch.size=", batch.size, " samples")
  env <- new.env(parent = emptyenv())
  env$cursor <- 0L
  nbatches <- nsample %/% batch.size
  list(
    reset = function() env$cursor <- 0L,
    iter.next = function() {
      env$cursor <- env$cursor + 1L
      env$cursor <= nbatches
    },
    value = function() {
      lo <- (env$cursor - 1L) * batch.size + 1L
      hi <- env$cursor * batch.size
      list(data = X[, lo:hi, drop = FALSE],
           label = y[, lo:hi, drop = FALSE])
    },
    nbatches = nbatches)
}

# per-batch mean negative log likelihood of the true tokens, from the
# (seq*batch, vocab) softmax output (reference calc.nll +
# mx.nd.choose.element.0index, rnn_model.R:83-97)
mx.rnn.batch.nll <- function(probs, label, batch.size) {
  flat <- as.integer(t(label))          # seq-major, matches sm rows
  picked <- probs[cbind(seq_along(flat), flat + 1L)]
  -sum(log(pmax(picked, 1e-10))) / batch.size
}

# training loop (reference train.rnn, rnn_model.R:100-225): per batch
# zero states, forward, backward, SGD-update the weight args; states
# stay zero (truncated BPTT at batch boundaries, like the reference
# which re-zeroes init states each batch)
mx.rnn.train <- function(model, train.data, eval.data = NULL,
                         num.round = 10, update.period = 1,
                         optimizer = "sgd", verbose = TRUE, ...) {
  if (update.period != 1)
    stop("mx.rnn.train: update.period > 1 needs grad.req='add', which ",
         "this binding does not expose; use update.period = 1")
  m <- model
  exec <- m$rnn.exec
  updater <- mx.opt.create.updater(optimizer,
                                   rescale.grad = 1 / m$batch.size, ...)
  out.shape <- c(m$num.label, m$seq.len * m$batch.size)
  zero.state <- array(0, dim = c(m$num.hidden, m$batch.size,
                                 m$num.rnn.layer))
  arg.params <- m$arg.params

  for (iteration in seq_len(num.round)) {
    train.data$reset()
    train.nll <- 0
    nbatch <- 0
    while (train.data$iter.next()) {
      batch <- train.data$value()
      mx.exec.set.arg(exec, "data", batch$data)
      mx.exec.set.arg(exec, "label", batch$label)
      for (nm in mx.rnn.state.names(m$mode))
        mx.exec.set.arg(exec, nm, zero.state)
      mx.exec.forward(exec, is.train = TRUE)
      mx.exec.backward(exec)
      for (nm in names(arg.params)) {
        grad <- mx.exec.get.grad(exec, nm, dim(arg.params[[nm]]))
        arg.params[[nm]] <- updater(nm, arg.params[[nm]], grad)
        mx.exec.set.arg(exec, nm, arg.params[[nm]])
      }
      probs <- mx.exec.get.output(exec, 1L, out.shape)
      train.nll <- train.nll +
        mx.rnn.batch.nll(t(probs), batch$label, m$batch.size)
      nbatch <- nbatch + m$seq.len
    }
    if (verbose)
      cat(sprintf("Iter [%d] Train: NLL=%.5f, Perp=%.5f\n", iteration,
                  train.nll / nbatch, exp(train.nll / nbatch)))
    if (!is.null(eval.data)) {
      eval.data$reset()
      val.nll <- 0
      nbatch <- 0
      while (eval.data$iter.next()) {
        batch <- eval.data$value()
        mx.exec.set.arg(exec, "data", batch$data)
        mx.exec.set.arg(exec, "label", batch$label)
        for (nm in mx.rnn.state.names(m$mode))
          mx.exec.set.arg(exec, nm, zero.state)
        mx.exec.forward(exec, is.train = FALSE)
        probs <- mx.exec.get.output(exec, 1L, out.shape)
        val.nll <- val.nll +
          mx.rnn.batch.nll(t(probs), batch$label, m$batch.size)
        nbatch <- nbatch + m$seq.len
      }
      if (verbose)
        cat(sprintf("Iter [%d] Val: NLL=%.5f, Perp=%.5f\n", iteration,
                    val.nll / nbatch, exp(val.nll / nbatch)))
    }
  }
  m$arg.params <- arg.params
  m
}

# shared driver behind mx.lstm / mx.gru / mx.rnn (each reference file
# repeats this block; lstm.R:152-241)
mx.rnn.create <- function(mode, train.data, eval.data = NULL,
                          num.rnn.layer, seq.len, num.hidden, num.embed,
                          num.label, batch.size, input.size,
                          ctx = mx.cpu(), num.round = 10,
                          update.period = 1,
                          initializer = mx.init.uniform(0.01),
                          dropout = 0, optimizer = "sgd", ...) {
  train.data <- mx.rnn.check.data(train.data, batch.size, TRUE)
  eval.data <- mx.rnn.check.data(eval.data, batch.size, FALSE)
  sym <- mx.rnn.train.symbol(mode, num.rnn.layer, num.hidden, num.embed,
                             num.label, input.size, dropout)
  model <- mx.rnn.setup.model(sym, mode, ctx, num.rnn.layer, seq.len,
                              num.hidden, num.embed, num.label,
                              batch.size, input.size, initializer)
  model <- mx.rnn.train(model, train.data, eval.data,
                        num.round = num.round,
                        update.period = update.period,
                        optimizer = optimizer, ...)
  structure(list(symbol = model$symbol, arg.params = model$arg.params,
                 aux.params = list(), mode = mode,
                 num.rnn.layer = num.rnn.layer, num.hidden = num.hidden,
                 num.embed = num.embed, num.label = num.label,
                 input.size = input.size),
            class = "MXFeedForwardModel")
}

# shared driver behind mx.*.inference (reference
# mx.lstm.inference, lstm.R:244-320): a seq.len=1 executor whose
# states persist across step calls
mx.rnn.infer.model <- function(mode, num.rnn.layer, input.size,
                             num.hidden, num.embed, num.label,
                             batch.size = 1, arg.params,
                             ctx = mx.cpu(), dropout = 0) {
  sym <- mx.rnn.inference.symbol(mode, num.rnn.layer, num.hidden,
                                 num.embed, num.label, input.size,
                                 dropout)
  model <- mx.rnn.setup.model(sym, mode, ctx, num.rnn.layer,
                              seq.len = 1, num.hidden, num.embed,
                              num.label, batch.size, input.size)
  for (nm in names(arg.params))
    if (nm %in% model$arg.names && mx.rnn.is.param.name(nm))
      mx.exec.set.arg(model$rnn.exec, nm, arg.params[[nm]])
  model$states <- lapply(mx.rnn.state.names(mode), function(nm)
    array(0, dim = c(num.hidden, batch.size, num.rnn.layer)))
  names(model$states) <- mx.rnn.state.names(mode)
  model
}

# one inference step (reference mx.lstm.forward, lstm.R:322-361):
# returns list(prob=, model=) with the carried states updated
mx.rnn.step <- function(model, input.data, new.seq = FALSE) {
  state.names <- mx.rnn.state.names(model$mode)
  state.dim <- c(model$num.hidden, model$batch.size, model$num.rnn.layer)
  if (new.seq)
    model$states <- lapply(model$states, function(s) array(0, state.dim))
  exec <- model$rnn.exec
  dim(input.data) <- c(1, model$batch.size)
  mx.exec.set.arg(exec, "data", input.data)
  for (nm in state.names) mx.exec.set.arg(exec, nm, model$states[[nm]])
  mx.exec.forward(exec, is.train = FALSE)
  prob <- mx.exec.get.output(exec, 1L,
                             c(model$num.label, model$batch.size))
  for (i in seq_along(state.names))
    model$states[[state.names[[i]]]] <-
      mx.exec.get.output(exec, 1L + i, state.dim)
  list(prob = prob, model = model)
}
