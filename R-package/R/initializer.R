# Weight initializers (reference R-package/R/initializer.R): each
# mx.init.* returns function(name, shape) -> R array. Shapes arrive in
# the package's R (column-major) convention: shape[length(shape)] is the
# C leading dim, so fan.out = last element, fan.in = prod of the rest —
# mirroring the reference's colmajor convention.

mx.init.internal.default <- function(name, shape) {
  if (grepl("bias$", name) || grepl("beta$", name)) return(array(0, dim = shape))
  if (grepl("gamma$", name)) return(array(1, dim = shape))
  NULL                                     # NULL: weight -> caller's rule
}

mx.init.uniform <- function(scale = 0.07) {
  function(name, shape) {
    fixed <- mx.init.internal.default(name, shape)
    if (!is.null(fixed)) return(fixed)
    array(runif(prod(shape), -scale, scale), dim = shape)
  }
}

mx.init.normal <- function(sd = 0.01) {
  function(name, shape) {
    fixed <- mx.init.internal.default(name, shape)
    if (!is.null(fixed)) return(fixed)
    array(rnorm(prod(shape), 0, sd), dim = shape)
  }
}

mx.init.Xavier <- function(rnd_type = "uniform", factor_type = "avg",
                           magnitude = 3) {
  function(name, shape) {
    fixed <- mx.init.internal.default(name, shape)
    if (!is.null(fixed)) return(fixed)
    n <- length(shape)
    fan.out <- shape[[n]]
    fan.in <- prod(shape[-n])
    factor <- switch(factor_type,
                     avg = (fan.in + fan.out) / 2,
                     "in" = fan.in,
                     out = fan.out,
                     stop("mx.init.Xavier: bad factor_type"))
    scale <- sqrt(magnitude / factor)
    if (identical(rnd_type, "uniform"))
      array(runif(prod(shape), -scale, scale), dim = shape)
    else
      array(rnorm(prod(shape), 0, scale), dim = shape)
  }
}
