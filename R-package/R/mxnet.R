# R frontend over the TPU runtime's C ABI.
#
# Parity target: the reference R-package's R/ layer (R-package/R/
# ndarray.R, symbol.R, executor.R, model.R) with the same user-facing
# naming (mx.nd.array, mx.symbol.*, mx.simple.bind, mx.model.*). The
# implementation is a fresh design over .Call stubs in src/mxnet_glue.c;
# operators are generated at load time from the registry enumeration
# (MXSymbolListAtomicSymbolCreators), exactly how the reference built
# mx.symbol.Convolution and friends from its C registry.
#
# Array convention: R stores column-major; the runtime is row-major
# (C order). Like the reference R-package, mx.nd.array() transposes on
# the way in and as.array() transposes back, so R users see R-natural
# indexing while buffers cross the ABI in C order.

# ---- context ---------------------------------------------------------------

mx.cpu <- function(dev.id = 0L) structure(
  list(device = "cpu", device_typeid = 1L, device_id = as.integer(dev.id)),
  class = "MXContext")

mx.tpu <- function(dev.id = 0L) structure(
  list(device = "tpu", device_typeid = 2L, device_id = as.integer(dev.id)),
  class = "MXContext")

# ---- layout marshalling helpers --------------------------------------------
# The package's central invariant: R column-major <-> runtime row-major.

.mx.to.c.order <- function(values) {
  if (inherits(values, "MXNDArray")) values <- as.array(values)
  if (!is.null(dim(values)))
    values <- aperm(values, rev(seq_along(dim(values))))
  as.double(values)
}

.mx.from.c.order <- function(values, shape) {
  arr <- array(values, dim = rev(shape))
  aperm(arr, rev(seq_along(shape)))
}

# ONE serializer for R values crossing the ABI as parameter strings —
# symbol params (mx.symbol.create) and iterator kwargs (mx.io.create)
# must not drift apart. force.tuple renders a length-1 numeric as a
# one-element tuple ("(3,)") for keys whose runtime type is a shape.
.mx.param.str <- function(v, force.tuple = FALSE) {
  if (is.logical(v)) return(if (v) "True" else "False")
  if (is.numeric(v) && length(v) > 1)
    return(paste0("(", paste(as.integer(v), collapse = ", "), ")"))
  if (force.tuple && is.numeric(v))
    return(paste0("(", as.integer(v), ",)"))
  as.character(v)
}

# ---- NDArray ---------------------------------------------------------------

mx.nd.array <- function(src.array, ctx = mx.cpu()) {
  if (is.null(dim(src.array))) dim(src.array) <- length(src.array)
  rdim <- dim(src.array)
  cdim <- rev(rdim)                       # row-major shape
  handle <- .Call(mxr_nd_create, as.integer(cdim), ctx$device_typeid,
                  ctx$device_id)
  .Call(mxr_nd_set, handle, .mx.to.c.order(src.array))
  structure(list(handle = handle), class = "MXNDArray")
}

mx.nd.zeros <- function(shape, ctx = mx.cpu()) {
  handle <- .Call(mxr_nd_create, as.integer(rev(shape)),
                  ctx$device_typeid, ctx$device_id)
  structure(list(handle = handle), class = "MXNDArray")
}

as.array.MXNDArray <- function(x, ...) {
  values <- .Call(mxr_nd_get, x$handle)
  cdim <- attr(values, "mx.dim")
  .mx.from.c.order(values, rev(cdim))
}

dim.MXNDArray <- function(x) rev(.Call(mxr_nd_shape, x$handle))

# empty device array with the same shape AND context as x (arithmetic
# on a tpu-resident array must stay on the tpu)
.mx.nd.like <- function(x) {
  ctx <- .Call(mxr_nd_context, x$handle)
  structure(
    list(handle = .Call(mxr_nd_create, .Call(mxr_nd_shape, x$handle),
                        ctx[[1]], ctx[[2]])), class = "MXNDArray")
}

# registered fixed-arity function on device arrays (reference
# R-package/src/ndarray.cc: mx.nd ops ride MXFuncInvoke)
.mx.nd.func <- function(name, nds, scalars = numeric(0), out = NULL) {
  if (is.null(out)) out <- .mx.nd.like(nds[[1]])
  .Call(mxr_func_invoke, name, lapply(nds, function(v) v$handle),
        as.numeric(scalars), out$handle)
  out
}

# arithmetic group generic (reference R-package/R/ndarray.R
# Ops.MXNDArray): +,-,*,/ between device arrays and scalars run on
# device through the registered _plus/_minus/... functions
Ops.MXNDArray <- function(e1, e2) {
  if (missing(e2)) {                       # unary +x / -x
    if (.Generic == "-")
      return(.mx.nd.func("_rminus_scalar", list(e1), 0))
    if (.Generic == "+")
      return(e1)
    stop("unary operator ", .Generic, " not supported on MXNDArray")
  }
  ops <- c("+" = "_plus", "-" = "_minus", "*" = "_mul", "/" = "_div")
  if (!(.Generic %in% names(ops)))
    stop("operator ", .Generic, " not supported on MXNDArray")
  nd1 <- inherits(e1, "MXNDArray")
  nd2 <- inherits(e2, "MXNDArray")
  if (nd1 && nd2)
    return(.mx.nd.func(ops[[.Generic]], list(e1, e2)))
  if (nd1) {                               # array <op> scalar
    scalar.op <- paste0(ops[[.Generic]], "_scalar")
    return(.mx.nd.func(scalar.op, list(e1), e2))
  }
  # scalar <op> array: + and * commute; - and / need reversed forms
  rev.op <- switch(.Generic, "+" = "_plus_scalar", "*" = "_mul_scalar",
                   "-" = "_rminus_scalar", "/" = "_rdiv_scalar")
  .mx.nd.func(rev.op, list(e2), e1)
}

mx.nd.save <- function(ndarray.list, filename) {
  handles <- lapply(ndarray.list, function(a) a$handle)
  .Call(mxr_nd_save, filename, handles)
  invisible(NULL)
}

mx.nd.load <- function(filename) {
  handles <- .Call(mxr_nd_load, filename)
  out <- lapply(handles, function(h)
    structure(list(handle = h), class = "MXNDArray"))
  names(out) <- names(handles)
  out
}

# ---- Symbol ----------------------------------------------------------------

mx.symbol.Variable <- function(name) structure(
  list(handle = .Call(mxr_sym_variable, name)), class = "MXSymbol")

mx.symbol.load.json <- function(json.str) structure(
  list(handle = .Call(mxr_sym_from_json, json.str)), class = "MXSymbol")

mx.symbol.load <- function(filename) structure(
  list(handle = .Call(mxr_sym_from_file, filename)), class = "MXSymbol")

mx.symbol.save <- function(symbol, filename) {
  .Call(mxr_sym_save_file, symbol$handle, filename)
  invisible(NULL)
}

# Gradient symbol wrt the named arguments (MXSymbolGrad): a bindable
# symbol whose outputs are d(sum(outputs))/d(arg).
mx.symbol.grad <- function(symbol, wrt) structure(
  list(handle = .Call(mxr_sym_grad, symbol$handle, as.character(wrt))),
  class = "MXSymbol")

print.MXSymbol <- function(x, ...) {
  cat(.Call(mxr_sym_print, x$handle), "\n")
  invisible(x)
}

mx.set.seed <- function(seed) {
  .Call(mxr_random_seed, as.integer(seed))
  invisible(NULL)
}

tojson.MXSymbol <- function(symbol) .Call(mxr_sym_to_json, symbol$handle)

arguments.MXSymbol <- function(symbol)
  .Call(mxr_sym_list_arguments, symbol$handle)

outputs.MXSymbol <- function(symbol)
  .Call(mxr_sym_list_outputs, symbol$handle)

# one output of a multi-output symbol as its own symbol; `sym[[i]]` is
# 1-based like everything in R (reference Symbol::GetOutput)
mx.symbol.get.output <- function(symbol, index) structure(
  list(handle = .Call(mxr_sym_get_output, symbol$handle,
                      as.integer(index - 1L))), class = "MXSymbol")

`[[.MXSymbol` <- function(x, i) mx.symbol.get.output(x, i)

# arithmetic group generic on SYMBOLS (reference R-package/R/symbol.R
# Ops.MXSymbol: graph-building +,-,*,/ dispatch to the registered
# _Plus/_Minus/... internal ops, so residual connections like
# `identity + conv` compose symbolically)
Ops.MXSymbol <- function(e1, e2) {
  ops <- c("+" = "_Plus", "-" = "_Minus", "*" = "_Mul", "/" = "_Div")
  if (missing(e2)) {                       # unary +x / -x
    if (.Generic == "-")
      return(mx.symbol.create("_MulScalar", e1, scalar = -1))
    if (.Generic == "+")
      return(e1)
    stop("unary operator ", .Generic, " not supported on MXSymbol")
  }
  if (!(.Generic %in% names(ops)))
    stop("operator ", .Generic, " not supported on MXSymbol")
  s1 <- inherits(e1, "MXSymbol")
  s2 <- inherits(e2, "MXSymbol")
  if (s1 && s2)
    return(mx.symbol.create(ops[[.Generic]], e1, e2))
  if (s1)                                  # symbol <op> scalar
    return(mx.symbol.create(paste0(ops[[.Generic]], "Scalar"), e1,
                            scalar = e2))
  # scalar <op> symbol: + and * commute; - and / need reversed forms
  rev.op <- switch(.Generic, "+" = "_PlusScalar", "*" = "_MulScalar",
                   "-" = "_RMinusScalar", "/" = "_RDivScalar")
  mx.symbol.create(rev.op, e2, scalar = e1)
}

mx.symbol.Group <- function(...) {
  syms <- list(...)
  if (length(syms) == 1 && is.list(syms[[1]]) &&
      !inherits(syms[[1]], "MXSymbol")) syms <- syms[[1]]
  structure(list(handle = .Call(mxr_sym_group,
                                lapply(syms, function(s) s$handle))),
            class = "MXSymbol")
}

mx.symbol.infer.shape <- function(symbol, ...) {
  shapes <- list(...)
  keys <- names(shapes)
  ind <- c(0L)
  data <- integer(0)
  for (s in shapes) {                     # R shape -> C row-major shape
    data <- c(data, as.integer(rev(s)))
    ind <- c(ind, length(data))
  }
  res <- .Call(mxr_sym_infer_shape, symbol$handle, keys,
               as.integer(ind), data)
  res$arg.shapes <- lapply(res$arg.shapes, rev)
  res$out.shapes <- lapply(res$out.shapes, rev)
  res$aux.shapes <- lapply(res$aux.shapes, rev)
  res
}

# internal: apply a registered operator (reference mx.varg.symbol.*).
# Symbol arguments may be positional (mx.symbol.Activation(net, ...)) or
# named (data=net); mixing positional and named symbol inputs follows
# the C ABI rule: either all inputs named or none.
mx.symbol.create <- function(op.name, ..., name = "") {
  args <- list(...)
  keys <- names(args)
  if (is.null(keys)) keys <- rep("", length(args))
  params <- list()
  pos.inputs <- list()
  named.inputs <- list()
  for (i in seq_along(args)) {
    v <- args[[i]]
    key <- keys[[i]]
    if (inherits(v, "MXSymbol")) {
      if (nzchar(key)) named.inputs[[key]] <- v
      else pos.inputs[[length(pos.inputs) + 1L]] <- v
    } else if (identical(key, "name")) {
      name <- v
    } else {
      if (!nzchar(key)) stop("non-symbol positional argument to ",
                             "mx.symbol.", op.name)
      params[[key]] <- v
    }
  }
  if (length(pos.inputs) > 0 && length(named.inputs) > 0)
    stop("mx.symbol.", op.name,
         ": use either all-named or all-positional symbol inputs")
  param.keys <- names(params)
  param.vals <- vapply(params, .mx.param.str, character(1))
  handle <- .Call(mxr_sym_create_atomic, op.name,
                  as.character(param.keys), as.character(param.vals))
  if (length(named.inputs) > 0) {
    in.keys <- as.character(names(named.inputs))
    in.handles <- lapply(named.inputs, function(s) s$handle)
  } else {
    in.keys <- character(0)
    in.handles <- lapply(pos.inputs, function(s) s$handle)
  }
  .Call(mxr_sym_compose, handle, name, in.keys, in.handles)
  structure(list(handle = handle), class = "MXSymbol")
}

# generated operator namespace: mx.symbol.Convolution(...) etc.
mx.symbol.list.operators <- function() .Call(mxr_sym_list_atomic)

.mx.generate.operators <- function(envir) {
  for (op in mx.symbol.list.operators()) {
    if (grepl("^_", op)) next
    fn <- local({
      op.name <- op
      function(..., name = "") mx.symbol.create(op.name, ..., name = name)
    })
    assign(paste0("mx.symbol.", op), fn, envir = envir)
  }
}

# ---- Executor --------------------------------------------------------------

mx.simple.bind <- function(symbol, ctx = mx.cpu(), grad.req = "write", ...) {
  if (!grad.req %in% c("write", "null"))
    stop("mx.simple.bind: unsupported grad.req '", grad.req,
         "' (this binding supports 'write' and 'null')")
  shapes <- list(...)
  keys <- names(shapes)
  ind <- c(0L)
  data <- integer(0)
  for (s in shapes) {
    data <- c(data, as.integer(rev(s)))
    ind <- c(ind, length(data))
  }
  handle <- .Call(mxr_exec_simple_bind, symbol$handle, ctx$device_typeid,
                  ctx$device_id, keys, as.integer(ind), data,
                  if (identical(grad.req, "null")) 0L else 1L)
  structure(list(handle = handle, symbol = symbol), class = "MXExecutor")
}

mx.exec.set.arg <- function(executor, name, values) {
  .Call(mxr_exec_set_arg, executor$handle, name, .mx.to.c.order(values))
  invisible(NULL)
}

mx.exec.forward <- function(executor, is.train = TRUE) {
  .Call(mxr_exec_forward, executor$handle, as.integer(is.train))
  invisible(NULL)
}

mx.exec.backward <- function(executor) {
  .Call(mxr_exec_backward, executor$handle)
  invisible(NULL)
}

mx.exec.get.output <- function(executor, index, shape) {
  if (index < 1L) stop("mx.exec.get.output: index is 1-based")
  values <- .Call(mxr_exec_get_output, executor$handle,
                  as.integer(index - 1L), as.integer(prod(shape)))
  .mx.from.c.order(values, shape)
}

mx.exec.get.grad <- function(executor, name, shape) {
  values <- .Call(mxr_exec_get_grad, executor$handle, name,
                  as.integer(prod(shape)))
  .mx.from.c.order(values, shape)
}

# ---- Model -----------------------------------------------------------------

# Load a reference-layout checkpoint: <prefix>-symbol.json +
# <prefix>-%04d.params with arg:/aux: key prefixes (reference
# R-package/R/model.R mx.model.load).
mx.model.load <- function(prefix, iteration) {
  symbol <- mx.symbol.load(sprintf("%s-symbol.json", prefix))
  params <- mx.nd.load(sprintf("%s-%04d.params", prefix, iteration))
  keys <- names(params)
  arg.params <- params[grepl("^arg:", keys)]
  names(arg.params) <- sub("^arg:", "", names(arg.params))
  aux.params <- params[grepl("^aux:", keys)]
  names(aux.params) <- sub("^aux:", "", names(aux.params))
  structure(list(symbol = symbol, arg.params = arg.params,
                 aux.params = aux.params), class = "MXFeedForwardModel")
}

mx.exec.set.aux <- function(executor, name, values) {
  .Call(mxr_exec_set_aux, executor$handle, name, .mx.to.c.order(values))
  invisible(NULL)
}

mx.exec.get.aux <- function(executor, name, shape) {
  values <- .Call(mxr_exec_get_aux, executor$handle, name,
                  as.integer(prod(shape)))
  .mx.from.c.order(values, shape)
}

# Forward inference on a batch (X in R layout: first dim = sample).
predict.MXFeedForwardModel <- function(object, X, ctx = mx.cpu(), ...) {
  data.shape <- dim(X)
  shapes <- mx.symbol.infer.shape(object$symbol, data = data.shape)
  executor <- mx.simple.bind(object$symbol, ctx, grad.req = "null",
                             data = data.shape)
  for (name in names(object$arg.params))
    mx.exec.set.arg(executor, name, object$arg.params[[name]])
  for (name in names(object$aux.params))   # BatchNorm moving stats etc.
    mx.exec.set.aux(executor, name, object$aux.params[[name]])
  mx.exec.set.arg(executor, "data", X)
  mx.exec.forward(executor, is.train = FALSE)
  out.shape <- shapes$out.shapes[[1]]
  mx.exec.get.output(executor, 1L, out.shape)
}

# One synchronous SGD step on a bound executor (the R-side analogue of
# perl-package/examples/train_step.pl): `params` is a named list of R
# arrays already set on the executor; returns the updated list.
mx.model.sgd.step <- function(executor, params, learning.rate = 0.01) {
  mx.exec.forward(executor, is.train = TRUE)
  mx.exec.backward(executor)
  for (name in names(params)) {
    grad <- mx.exec.get.grad(executor, name, dim(params[[name]]))
    params[[name]] <- params[[name]] - learning.rate * grad
    mx.exec.set.arg(executor, name, params[[name]])
  }
  params
}


# Registered optimizer over the C surface (MXOptimizerCreateOptimizer):
# per-index state lives on the native handle, lr/wd are per-call.
mx.opt.create <- function(name, ...) {
  params <- list(...)
  structure(list(handle = .Call(mxr_opt_create, name,
                                as.character(names(params)),
                                as.character(unlist(params)))),
            class = "MXOptimizer")
}

mx.opt.update <- function(optimizer, index, weight, grad,
                          learning.rate = 0.01, wd = 0.0) {
  .Call(mxr_opt_update, optimizer$handle, as.integer(index),
        weight$handle, grad$handle, learning.rate, wd)
  invisible(NULL)
}
