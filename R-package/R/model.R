# FeedForward model training API (reference R-package/R/model.R:1-562):
# mx.model.FeedForward.create drives the full loop — infer shapes, init
# params, bind one executor, per batch set data/label + forward +
# backward + updater, per epoch metric/eval/callback — and returns an
# MXFeedForwardModel(symbol, arg.params, aux.params) usable by
# predict() and mx.model.save/load.
#
# Layout: the package's internal convention is colmajor — X dim =
# (feature..., nsample) in R, which crosses the ABI as C
# (nsample, feature...). array.layout = "rowmajor" transposes matrices
# on the way in, "auto" guesses like the reference
# (mx.model.select.layout.train, model.R:285-307).

mx.model.check.arguments <- function(symbol) {
  data <- NULL
  label <- NULL
  for (nm in arguments.MXSymbol(symbol)) {
    if (endsWith(nm, "data")) {
      if (!is.null(data)) stop("model must have exactly one data argument")
      data <- nm
    }
    if (endsWith(nm, "label")) {
      if (!is.null(label)) stop("model must have exactly one label argument")
      label <- nm
    }
  }
  if (is.null(data) || is.null(label))
    stop("model needs one data and one label argument")
  c(data, label)
}

mx.model.select.layout.train <- function(X, array.layout = "auto") {
  if (identical(array.layout, "auto")) {
    # heuristic as in the reference: more columns than rows usually
    # means (feature, nsample) already
    array.layout <- if (!is.null(dim(X)) && length(dim(X)) == 2 &&
                        nrow(X) > ncol(X)) "rowmajor" else "colmajor"
  }
  if (identical(array.layout, "rowmajor") && length(dim(X)) == 2) X <- t(X)
  X
}

mx.model.init.params <- function(symbol, input.shape, initializer) {
  shapes <- mx.symbol.infer.shape(symbol, data = input.shape)
  if (is.null(shapes)) stop("cannot infer shapes from input.shape")
  arg.names <- arguments.MXSymbol(symbol)
  arg.params <- list()
  for (i in seq_along(arg.names)) {
    nm <- arg.names[[i]]
    if (nm %in% c("data") || endsWith(nm, "label")) next
    arg.params[[nm]] <- initializer(nm, shapes$arg.shapes[[i]])
  }
  aux.params <- list()
  aux.names <- names(shapes$aux.shapes)
  for (i in seq_along(shapes$aux.shapes)) {
    nm <- if (!is.null(aux.names)) aux.names[[i]] else sprintf("aux%d", i)
    # moving variances start at 1, everything else at 0 (runtime rule)
    init.val <- if (grepl("var$", nm)) 1 else 0
    aux.params[[nm]] <- array(init.val, dim = shapes$aux.shapes[[i]])
  }
  list(arg.params = arg.params, aux.params = aux.params,
       shapes = shapes, arg.names = arg.names)
}

mx.model.FeedForward.create <- function(
    symbol, X, y = NULL, ctx = mx.cpu(), num.round = 10,
    array.batch.size = 128, optimizer = "sgd",
    initializer = mx.init.uniform(0.01), eval.data = NULL,
    eval.metric = mx.metric.accuracy, epoch.end.callback = NULL,
    batch.end.callback = NULL, array.layout = "auto", verbose = TRUE, ...) {
  names2 <- mx.model.check.arguments(symbol)
  data.name <- names2[[1]]
  label.name <- names2[[2]]

  if (is.list(X) && is.function(X$iter.next)) {
    # X is already a data iterator (mx.io.arrayiter / ImageRecordIter /
    # MNISTIter / CSVIter ... — the reference accepts either form);
    # probe one batch for the input shape, then rewind
    iter <- X
    iter$reset()
    if (!iter$iter.next())
      stop("mx.model.FeedForward.create: the data iterator is empty")
    probe <- iter$value()
    input.shape <- dim(probe$data)
    iter$reset()
  } else {
    X <- mx.model.select.layout.train(X, array.layout)
    iter <- mx.io.arrayiter(X, y, batch.size = array.batch.size,
                            shuffle = TRUE)
    dshape <- dim(X)
    input.shape <- c(dshape[-length(dshape)], array.batch.size)
  }
  init <- mx.model.init.params(symbol, input.shape, initializer)
  arg.params <- init$arg.params
  aux.params <- init$aux.params
  shapes <- init$shapes
  arg.names <- init$arg.names
  shape.of <- function(nm) shapes$arg.shapes[[match(nm, arg.names)]]

  exec.args <- list(symbol = symbol, ctx = ctx, grad.req = "write")
  exec.args[[data.name]] <- input.shape
  executor <- do.call(mx.simple.bind, exec.args)
  for (nm in names(arg.params)) mx.exec.set.arg(executor, nm, arg.params[[nm]])
  for (nm in names(aux.params)) mx.exec.set.aux(executor, nm, aux.params[[nm]])

  updater <- mx.opt.create.updater(optimizer, ...)
  out.shape <- shapes$out.shapes[[1]]
  env <- new.env()
  env$metric <- eval.metric

  for (iteration in seq_len(num.round)) {
    iter$reset()
    env$train.metric.state <- eval.metric$init()
    nbatch <- 0
    while (iter$iter.next()) {
      batch <- iter$value()
      nbatch <- nbatch + 1
      mx.exec.set.arg(executor, data.name, batch$data)
      mx.exec.set.arg(executor, label.name, batch$label)
      mx.exec.forward(executor, is.train = TRUE)
      mx.exec.backward(executor)
      for (nm in names(arg.params)) {
        grad <- mx.exec.get.grad(executor, nm, dim(arg.params[[nm]]))
        arg.params[[nm]] <- updater(nm, arg.params[[nm]], grad)
        mx.exec.set.arg(executor, nm, arg.params[[nm]])
      }
      pred <- mx.exec.get.output(executor, 1L, out.shape)
      env$train.metric.state <- eval.metric$update(
        batch$label, pred, env$train.metric.state)
      if (!is.null(batch.end.callback))
        batch.end.callback(iteration, nbatch, env)
    }
    res <- eval.metric$get(env$train.metric.state)
    if (verbose)
      cat(sprintf("Epoch [%d] Train-%s=%f\n", iteration, res$name, res$value))

    if (!is.null(eval.data)) {
      eval.state <- eval.metric$init()
      eval.data$reset()
      while (eval.data$iter.next()) {
        batch <- eval.data$value()
        mx.exec.set.arg(executor, data.name, batch$data)
        mx.exec.forward(executor, is.train = FALSE)
        pred <- mx.exec.get.output(executor, 1L, out.shape)
        eval.state <- eval.metric$update(batch$label, pred, eval.state)
      }
      res <- eval.metric$get(eval.state)
      if (verbose)
        cat(sprintf("Epoch [%d] Validation-%s=%f\n",
                    iteration, res$name, res$value))
    }

    for (nm in names(aux.params))          # pull updated moving stats
      aux.params[[nm]] <- mx.exec.get.aux(executor, nm,
                                          dim(aux.params[[nm]]))
    env$model <- structure(list(symbol = symbol, arg.params = arg.params,
                                aux.params = aux.params),
                           class = "MXFeedForwardModel")
    if (!is.null(epoch.end.callback))
      if (identical(epoch.end.callback(iteration, 0, env), FALSE)) break
  }
  env$model
}

# Save in the reference checkpoint layout (<prefix>-symbol.json +
# <prefix>-%04d.params with arg:/aux: key prefixes) so R-written
# checkpoints load from Python and vice versa.
mx.model.save <- function(model, prefix, iteration) {
  mx.symbol.save(model$symbol, sprintf("%s-symbol.json", prefix))
  all <- list()
  for (nm in names(model$arg.params))
    all[[paste0("arg:", nm)]] <- mx.nd.array(model$arg.params[[nm]])
  for (nm in names(model$aux.params))
    all[[paste0("aux:", nm)]] <- mx.nd.array(model$aux.params[[nm]])
  mx.nd.save(all, sprintf("%s-%04d.params", prefix, iteration))
  invisible(NULL)
}

# mx.mlp convenience wrapper (reference R-package/R/mlp.R): build a
# softmax MLP and train it in one call.
mx.mlp <- function(data, label, hidden_node = 1, out_node = 2,
                   dropout = NULL, activation = "relu",
                   out_activation = "softmax", ...) {
  net <- mx.symbol.Variable("data")
  i <- 1
  for (h in hidden_node) {
    net <- mx.symbol.create("FullyConnected", data = net, num_hidden = h,
                            name = sprintf("fc%d", i))
    net <- mx.symbol.create("Activation", data = net,
                            act_type = activation,
                            name = sprintf("act%d", i))
    if (!is.null(dropout))
      net <- mx.symbol.create("Dropout", data = net, p = dropout,
                              name = sprintf("drop%d", i))
    i <- i + 1
  }
  net <- mx.symbol.create("FullyConnected", data = net,
                          num_hidden = out_node, name = "fc_out")
  if (!identical(out_activation, "softmax"))
    stop("mx.mlp: only softmax output supported")
  net <- mx.symbol.create("SoftmaxOutput", data = net, name = "softmax")
  mx.model.FeedForward.create(net, X = data, y = label, ...)
}
