# Package hooks: generate mx.symbol.<Op> functions from the registry at
# load time (reference R-package/R/zzz.R mx.symbol.infer the same way:
# its init.symbol.methods walked the C registry).
.onLoad <- function(libname, pkgname) {
  ns <- asNamespace(pkgname)
  tryCatch({
    .mx.generate.operators(ns)
    # export the generated creators so library() users see them (the
    # static NAMESPACE cannot list load-time-generated names)
    generated <- ls(ns, pattern = "^mx\\.symbol\\.")
    namespaceExport(ns, generated)
  }, error = function(e)
    packageStartupMessage("mxnet.tpu: operator generation ",
                          "deferred (", conditionMessage(e), ")"))
}
