# GRU training / inference API (reference R-package/R/gru.R:1-355; the
# reference hand-builds update/reset gates per timestep, gru.R:1-46 —
# here the fused scan-based `RNN` symbol runs the same recurrence, see
# rnn_model.R). Entry points and argument names match the reference.

#' Train a GRU language-model (reference mx.gru, gru.R:150-239)
mx.gru <- function(train.data, eval.data = NULL,
                   num.gru.layer, seq.len,
                   num.hidden, num.embed, num.label,
                   batch.size, input.size,
                   ctx = mx.cpu(),
                   num.round = 10, update.period = 1,
                   initializer = mx.init.uniform(0.01),
                   dropout = 0, optimizer = "sgd", ...) {
  mx.rnn.create("gru", train.data, eval.data,
                num.rnn.layer = num.gru.layer, seq.len = seq.len,
                num.hidden = num.hidden, num.embed = num.embed,
                num.label = num.label, batch.size = batch.size,
                input.size = input.size, ctx = ctx,
                num.round = num.round, update.period = update.period,
                initializer = initializer, dropout = dropout,
                optimizer = optimizer, ...)
}

#' Single-step GRU inference model (reference mx.gru.inference,
#' gru.R:242-316)
mx.gru.inference <- function(num.gru.layer, input.size, num.hidden,
                             num.embed, num.label, batch.size = 1,
                             arg.params, ctx = mx.cpu(), dropout = 0) {
  mx.rnn.infer.model("gru", num.rnn.layer = num.gru.layer,
                   input.size = input.size, num.hidden = num.hidden,
                   num.embed = num.embed, num.label = num.label,
                   batch.size = batch.size, arg.params = arg.params,
                   ctx = ctx, dropout = dropout)
}

#' One forward step of a GRU inference model (reference mx.gru.forward,
#' gru.R:318-355)
mx.gru.forward <- function(model, input.data, new.seq = FALSE) {
  mx.rnn.step(model, input.data, new.seq)
}
