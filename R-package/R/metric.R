# Evaluation metrics (reference R-package/R/metric.R): a metric is a
# list(init, update, get) built by mx.metric.custom. Predictions use the
# package's colmajor convention: pred dim = (nclass, batch), label is a
# length-batch vector of 0-based class ids (matching the C runtime).

mx.metric.custom <- function(name, feval) {
  list(
    name = name,
    init = function() list(sum = 0, n = 0),
    update = function(label, pred, state) {
      state$sum <- state$sum + feval(label, pred)
      state$n <- state$n + 1
      state
    },
    get = function(state) list(name = name, value = state$sum / max(state$n, 1))
  )
}

mx.metric.accuracy <- mx.metric.custom("accuracy", function(label, pred) {
  guess <- max.col(t(pred)) - 1           # pred (nclass, batch) colmajor
  mean(guess == as.vector(label))
})

mx.metric.mse <- mx.metric.custom("mse", function(label, pred) {
  mean((as.vector(label) - as.vector(pred))^2)
})

mx.metric.rmse <- mx.metric.custom("rmse", function(label, pred) {
  sqrt(mean((as.vector(label) - as.vector(pred))^2))
})

mx.metric.mae <- mx.metric.custom("mae", function(label, pred) {
  mean(abs(as.vector(label) - as.vector(pred)))
})
