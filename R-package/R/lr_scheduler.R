# Learning-rate schedulers (reference R-package/R/lr_scheduler.R /
# python lr_scheduler.py): a scheduler maps the update count to a lr.

mx.lr_scheduler.FactorScheduler <- function(step, factor_val = 1,
                                            stop_factor_lr = 1e-8) {
  function(base.lr, num.update) {
    lr <- base.lr * factor_val ^ (num.update %/% step)
    max(lr, stop_factor_lr)
  }
}

mx.lr_scheduler.MultiFactorScheduler <- function(steps, factor_val = 1,
                                                 stop_factor_lr = 1e-8) {
  function(base.lr, num.update) {
    lr <- base.lr * factor_val ^ sum(num.update >= steps)
    max(lr, stop_factor_lr)
  }
}
