# FeedForward training (reference R-package/tests/testthat/
# test_model.R trained MNIST; this trains a separable synthetic task
# so it runs offline). The same training sequence is executed natively
# in CI by tests/r_glue_train.c (convergence >= 0.9).
require(mxnet.tpu)

context("models")

test_that("feedforward model converges", {
  set.seed(7)
  n <- 400
  y <- sample(0:1, n, replace = TRUE)
  X <- matrix(rnorm(n * 8), 8, n) + rep(y * 1.5, each = 8)

  data <- mx.symbol.Variable("data")
  net <- mx.symbol.FullyConnected(data, name = "fc1", num_hidden = 16)
  net <- mx.symbol.create("Activation", net, act_type = "relu")
  net <- mx.symbol.FullyConnected(net, name = "fc2", num_hidden = 2)
  net <- mx.symbol.create("SoftmaxOutput", net, name = "softmax")

  model <- mx.model.FeedForward.create(
    net, X = X, y = y, num.round = 8, array.batch.size = 32,
    learning.rate = 0.1, momentum = 0.9,
    array.layout = "colmajor", verbose = FALSE)

  pred <- predict(model, X, array.layout = "colmajor")
  acc <- mean(max.col(t(pred)) - 1 == y)
  expect_true(acc > 0.9)
})

test_that("checkpoint save/load round-trip", {
  data <- mx.symbol.Variable("data")
  net <- mx.symbol.FullyConnected(data, name = "fc", num_hidden = 2)
  net <- mx.symbol.create("SoftmaxOutput", net, name = "softmax")
  model <- mx.model.FeedForward.create(
    net, X = matrix(rnorm(64), 4, 16), y = sample(0:1, 16, TRUE),
    num.round = 1, array.batch.size = 8, array.layout = "colmajor",
    verbose = FALSE)
  prefix <- tempfile()
  mx.model.save(model, prefix, 1)
  loaded <- mx.model.load(prefix, 1)
  expect_equal(sort(names(loaded$arg.params)),
               sort(names(model$arg.params)))
})
