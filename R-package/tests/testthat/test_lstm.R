# LSTM training tier (reference R-package/tests/testthat/test_lstm.R
# trained a small lstm unroll). Trains mx.lstm on a deterministic
# cyclic-token task and steps the stateful inference model — the same
# sequence tests/r_glue_rnn_train.c executes natively in CI (train and
# inference accuracy both gated >= 0.9 there).
require(mxnet.tpu)

context("lstm")

test_that("mx.lstm trains and mx.lstm.forward carries state", {
  vocab <- 8
  seq.len <- 8
  batch.size <- 8
  n.seq <- 32
  X <- matrix(0L, seq.len, n.seq)
  Y <- matrix(0L, seq.len, n.seq)
  for (s in seq_len(n.seq)) {
    start <- (s - 1) %% vocab
    X[, s] <- (start + 0:(seq.len - 1)) %% vocab
    Y[, s] <- (start + 1:seq.len) %% vocab
  }

  model <- mx.lstm(list(data = X, label = Y),
                   num.lstm.layer = 1, seq.len = seq.len,
                   num.hidden = 16, num.embed = 8, num.label = vocab,
                   batch.size = batch.size, input.size = vocab,
                   num.round = 20, learning.rate = 0.3)
  expect_true(inherits(model, "MXFeedForwardModel"))

  infer <- mx.lstm.inference(num.lstm.layer = 1, input.size = vocab,
                             num.hidden = 16, num.embed = 8,
                             num.label = vocab, batch.size = 1,
                             arg.params = model$arg.params)
  correct <- 0
  step <- mx.lstm.forward(infer, 0, new.seq = TRUE)
  for (t in 1:(seq.len - 1)) {
    step <- mx.lstm.forward(step$model, t %% vocab)
    guess <- which.max(as.numeric(step$prob)) - 1
    if (guess == (t + 1) %% vocab) correct <- correct + 1
  }
  expect_true(correct / (seq.len - 1) > 0.7)
})
