# NDArray arithmetic and IO (reference
# R-package/tests/testthat/test_ndarray.R): the Ops.MXNDArray group
# generic must match R arithmetic elementwise, including reversed
# scalar operands. No R runtime exists in this image's CI, so the same
# op sequence is executed natively by tests/r_glue_rnn_train.c
# (func_invoke_ok); this file runs under testthat wherever R exists.
require(mxnet.tpu)

context("ndarray")

test_that("element-wise calculation for vector", {
  x <- as.numeric(1:10)
  mat <- mx.nd.array(as.array(x), mx.cpu(0))
  expect_equal(x, as.numeric(as.array(mat)))
  expect_equal(x + 1, as.numeric(as.array(mat + 1)))
  expect_equal(x - 10, as.numeric(as.array(mat - 10)))
  expect_equal(x * 20, as.numeric(as.array(mat * 20)))
  expect_equal(x / 3, as.numeric(as.array(mat / 3)), tolerance = 1e-5)
  expect_equal(-1 - x, as.numeric(as.array(-1 - mat)))
  expect_equal(-5 / x, as.numeric(as.array(-5 / mat)), tolerance = 1e-5)
  expect_equal(x + x, as.numeric(as.array(mat + mat)))
  expect_equal(x / x, as.numeric(as.array(mat / mat)))
  expect_equal(x * x, as.numeric(as.array(mat * mat)))
  expect_equal(x - x, as.numeric(as.array(mat - mat)))
  expect_equal(as.numeric(as.array(1 - mat)), 1 - x)
})

test_that("element-wise calculation for matrix", {
  x <- matrix(as.numeric(1:4), 2, 2)
  mat <- mx.nd.array(as.array(x), mx.cpu(0))
  expect_equal(x, as.array(mat))
  expect_equal(x + 1, as.array(mat + 1))
  expect_equal(x * 20, as.array(mat * 20))
  expect_equal(x / 3, as.array(mat / 3), tolerance = 1e-5)
  expect_equal(x * x, as.array(mat * mat))
})

test_that("save/load round-trip", {
  x <- matrix(as.numeric(1:6), 2, 3)
  path <- tempfile(fileext = ".nd")
  mx.nd.save(list(w = mx.nd.array(x)), path)
  back <- mx.nd.load(path)
  expect_equal(as.array(back[["w"]]), x)
})
