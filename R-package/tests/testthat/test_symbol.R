# Symbol composition (reference R-package/tests/testthat/test_symbol.R).
require(mxnet.tpu)

context("symbol")

test_that("basic symbol operation", {
  data <- mx.symbol.Variable("data")
  net1 <- mx.symbol.FullyConnected(data = data, name = "fc1",
                                   num_hidden = 10)
  net1 <- mx.symbol.FullyConnected(data = net1, name = "fc2",
                                   num_hidden = 100)
  expect_equal(arguments.MXSymbol(net1),
               c("data", "fc1_weight", "fc1_bias",
                 "fc2_weight", "fc2_bias"))
})

test_that("shape inference", {
  data <- mx.symbol.Variable("data")
  net <- mx.symbol.FullyConnected(data = data, name = "fc",
                                  num_hidden = 8)
  shapes <- mx.symbol.infer.shape(net, data = c(5, 32))
  expect_equal(shapes$out.shapes[[1]], c(8, 32))
})

test_that("multi-output select and group", {
  s <- mx.symbol.create("SliceChannel", mx.symbol.Variable("x"),
                        num_outputs = 2, name = "split")
  expect_equal(length(outputs.MXSymbol(s)), 2)
  g <- mx.symbol.Group(list(s[[1]], s[[2]]))
  expect_equal(length(outputs.MXSymbol(g)), 2)
})

test_that("json round-trip", {
  net <- mx.symbol.FullyConnected(mx.symbol.Variable("data"),
                                  name = "fc", num_hidden = 4)
  j <- tojson.MXSymbol(net)
  back <- mx.symbol.load.json(j)
  expect_equal(arguments.MXSymbol(back), arguments.MXSymbol(net))
})
