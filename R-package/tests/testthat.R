# Standard testthat runner (R CMD check entry point). CI in this image
# has no R runtime; the native twins of these tests run in
# tests/test_r_package.py through the real .Call glue.
library(testthat)
library(mxnet.tpu)

test_check("mxnet.tpu")
