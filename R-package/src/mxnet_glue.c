/*
 * .Call glue between R and the framework's C ABI (libmxtpu).
 *
 * Parity target: the reference R-package's src/ layer
 * (R-package/src/ndarray.cc, symbol.cc, executor.cc — Rcpp modules over
 * include/mxnet/c_api.h). This re-design uses the plain R C API (.Call /
 * SEXP) instead of Rcpp so the package has zero compile-time deps beyond
 * R itself, and targets the TPU runtime ABI (include/mxnet_tpu/c_api.h).
 *
 * Handles cross into R as external pointers with finalizers; tensors
 * cross as R numeric vectors with a dim attribute (row-major order is
 * converted on the R side; buffers here are the C-order floats the ABI
 * expects).
 *
 * Built by R CMD INSTALL against an installed libmxtpu.so (see
 * src/Makevars); this directory cannot be compiled without R headers,
 * which is also true of the reference's R glue.
 */
#include <R.h>
#include <Rinternals.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include <mxnet_tpu/c_api.h>   /* via PKG_CPPFLAGS -I$(MXTPU_HOME)/include */

/* ---- helpers ---------------------------------------------------------- */

static void chk(int rc) {
  if (rc != 0) Rf_error("mxnet_tpu: %s", MXGetLastError());
}

static void ndarray_finalizer(SEXP ptr) {
  NDArrayHandle h = R_ExternalPtrAddr(ptr);
  if (h) { MXNDArrayFree(h); R_ClearExternalPtr(ptr); }
}

static void symbol_finalizer(SEXP ptr) {
  SymbolHandle h = R_ExternalPtrAddr(ptr);
  if (h) { MXSymbolFree(h); R_ClearExternalPtr(ptr); }
}

static void executor_finalizer(SEXP ptr) {
  ExecutorHandle h = R_ExternalPtrAddr(ptr);
  if (h) { MXExecutorFree(h); R_ClearExternalPtr(ptr); }
}

static SEXP wrap_handle(void *h, R_CFinalizer_t fin) {
  SEXP ptr = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ptr, fin, TRUE);
  UNPROTECT(1);
  return ptr;
}

static SEXP charvec(mx_uint n, const char **strs) {
  SEXP out = PROTECT(Rf_allocVector(STRSXP, n));
  for (mx_uint i = 0; i < n; ++i)
    SET_STRING_ELT(out, i, Rf_mkChar(strs[i]));
  UNPROTECT(1);
  return out;
}

/* ---- NDArray ---------------------------------------------------------- */

/* mxr_nd_create(shape_intvec, dev_type, dev_id) -> extptr */
SEXP mxr_nd_create(SEXP shape, SEXP dev_type, SEXP dev_id) {
  mx_uint ndim = (mx_uint)Rf_length(shape);
  mx_uint *dims = (mx_uint *)R_alloc(ndim, sizeof(mx_uint));
  for (mx_uint i = 0; i < ndim; ++i) dims[i] = (mx_uint)INTEGER(shape)[i];
  NDArrayHandle h;
  chk(MXNDArrayCreate(dims, ndim, Rf_asInteger(dev_type),
                      Rf_asInteger(dev_id), &h));
  return wrap_handle(h, ndarray_finalizer);
}

/* mxr_nd_set(extptr, numeric) — host->device copy */
SEXP mxr_nd_set(SEXP ptr, SEXP values) {
  NDArrayHandle h = R_ExternalPtrAddr(ptr);
  R_xlen_t n = Rf_xlength(values);
  float *buf = (float *)R_alloc(n, sizeof(float));
  double *src = REAL(values);
  for (R_xlen_t i = 0; i < n; ++i) buf[i] = (float)src[i];
  chk(MXNDArraySyncCopyFromCPU(h, buf, (mx_uint)n));
  return R_NilValue;
}

/* mxr_nd_get(extptr) -> numeric with dim attribute (C order) */
SEXP mxr_nd_get(SEXP ptr) {
  NDArrayHandle h = R_ExternalPtrAddr(ptr);
  mx_uint ndim;
  const mx_uint *dims;
  chk(MXNDArrayGetShape(h, &ndim, &dims));
  R_xlen_t n = 1;
  for (mx_uint i = 0; i < ndim; ++i) n *= dims[i];
  float *buf = (float *)R_alloc(n, sizeof(float));
  chk(MXNDArraySyncCopyToCPU(h, buf, (mx_uint)n));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, n));
  for (R_xlen_t i = 0; i < n; ++i) REAL(out)[i] = buf[i];
  SEXP dim = PROTECT(Rf_allocVector(INTSXP, ndim));
  for (mx_uint i = 0; i < ndim; ++i) INTEGER(dim)[i] = (int)dims[i];
  Rf_setAttrib(out, Rf_install("mx.dim"), dim);
  UNPROTECT(2);
  return out;
}

SEXP mxr_nd_shape(SEXP ptr) {
  NDArrayHandle h = R_ExternalPtrAddr(ptr);
  mx_uint ndim;
  const mx_uint *dims;
  chk(MXNDArrayGetShape(h, &ndim, &dims));
  SEXP out = PROTECT(Rf_allocVector(INTSXP, ndim));
  for (mx_uint i = 0; i < ndim; ++i) INTEGER(out)[i] = (int)dims[i];
  UNPROTECT(1);
  return out;
}

/* mxr_nd_save(fname, list_of_extptr_named) */
SEXP mxr_nd_save(SEXP fname, SEXP arrays) {
  mx_uint n = (mx_uint)Rf_length(arrays);
  NDArrayHandle *handles =
      (NDArrayHandle *)R_alloc(n, sizeof(NDArrayHandle));
  const char **keys = (const char **)R_alloc(n, sizeof(char *));
  SEXP names = Rf_getAttrib(arrays, R_NamesSymbol);
  for (mx_uint i = 0; i < n; ++i) {
    handles[i] = R_ExternalPtrAddr(VECTOR_ELT(arrays, i));
    if (names != R_NilValue) keys[i] = CHAR(STRING_ELT(names, i));
  }
  /* NULL keys = unnamed container (loads back as a positional list) */
  chk(MXNDArraySave(CHAR(STRING_ELT(fname, 0)), n, handles,
                    (names == R_NilValue) ? NULL : keys));
  return R_NilValue;
}

/* Loaded arrays are owned collectively by the load record
 * (MXNDArrayListFree frees record AND handles), so each R wrapper
 * carries the same token in its 'prot' slot: only when every wrapper
 * is collected does the token finalizer release the whole list. */
struct LoadTok {
  NDArrayHandle *arr;
  mx_uint size;
  const char **names;
};

static void loadlist_finalizer(SEXP ptr) {
  struct LoadTok *tok = (struct LoadTok *)R_ExternalPtrAddr(ptr);
  if (tok) {
    MXNDArrayListFree(tok->arr, tok->size, tok->names);
    free(tok);
    R_ClearExternalPtr(ptr);
  }
}

/* mxr_nd_load(fname) -> named list of extptr */
SEXP mxr_nd_load(SEXP fname) {
  mx_uint size, name_size;
  NDArrayHandle *arrs;
  const char **names;
  chk(MXNDArrayLoad(CHAR(STRING_ELT(fname, 0)), &size, &arrs,
                    &name_size, &names));
  struct LoadTok *tok = (struct LoadTok *)malloc(sizeof(struct LoadTok));
  tok->arr = arrs;
  tok->size = size;
  tok->names = names;
  SEXP token = PROTECT(R_MakeExternalPtr(tok, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(token, loadlist_finalizer, TRUE);
  SEXP out = PROTECT(Rf_allocVector(VECSXP, size));
  for (mx_uint i = 0; i < size; ++i)
    /* no per-handle finalizer: the token releases the whole list */
    SET_VECTOR_ELT(out, i, R_MakeExternalPtr(arrs[i], R_NilValue, token));
  if (name_size == size) {
    SEXP nm = PROTECT(charvec(size, names));
    Rf_setAttrib(out, R_NamesSymbol, nm);
    UNPROTECT(1);
  }
  UNPROTECT(2);
  return out;
}

/* ---- Symbol ----------------------------------------------------------- */

SEXP mxr_sym_from_json(SEXP json) {
  SymbolHandle h;
  chk(MXSymbolCreateFromJSON(CHAR(STRING_ELT(json, 0)), &h));
  return wrap_handle(h, symbol_finalizer);
}

SEXP mxr_sym_to_json(SEXP ptr) {
  const char *json;
  chk(MXSymbolSaveToJSON(R_ExternalPtrAddr(ptr), &json));
  return Rf_mkString(json);
}

SEXP mxr_sym_variable(SEXP name) {
  SymbolHandle h;
  chk(MXSymbolCreateVariable(CHAR(STRING_ELT(name, 0)), &h));
  return wrap_handle(h, symbol_finalizer);
}

SEXP mxr_sym_list_arguments(SEXP ptr) {
  mx_uint n;
  const char **names;
  chk(MXSymbolListArguments(R_ExternalPtrAddr(ptr), &n, &names));
  return charvec(n, names);
}

SEXP mxr_sym_list_outputs(SEXP ptr) {
  mx_uint n;
  const char **names;
  chk(MXSymbolListOutputs(R_ExternalPtrAddr(ptr), &n, &names));
  return charvec(n, names);
}

SEXP mxr_sym_list_auxiliary(SEXP ptr) {
  mx_uint n;
  const char **names;
  chk(MXSymbolListAuxiliaryStates(R_ExternalPtrAddr(ptr), &n, &names));
  return charvec(n, names);
}

/* registry: list operator names */
SEXP mxr_sym_list_atomic(void) {
  mx_uint n;
  AtomicSymbolCreator *creators;
  chk(MXSymbolListAtomicSymbolCreators(&n, &creators));
  SEXP out = PROTECT(Rf_allocVector(STRSXP, n));
  for (mx_uint i = 0; i < n; ++i) {
    const char *name;
    chk(MXSymbolGetAtomicSymbolName(creators[i], &name));
    SET_STRING_ELT(out, i, Rf_mkChar(name));
  }
  UNPROTECT(1);
  return out;
}

/* name -> creator lookup, cached for the process lifetime (creator
 * handles are stable per the ABI contract) */
static AtomicSymbolCreator lookup_creator(const char *opname) {
  static mx_uint nc = 0;
  static AtomicSymbolCreator *creators = NULL;
  static const char **names = NULL;
  if (creators == NULL) {
    chk(MXSymbolListAtomicSymbolCreators(&nc, &creators));
    names = (const char **)malloc(nc * sizeof(char *));
    for (mx_uint i = 0; i < nc; ++i)
      chk(MXSymbolGetAtomicSymbolName(creators[i], &names[i]));
  }
  for (mx_uint i = 0; i < nc; ++i)
    if (strcmp(names[i], opname) == 0) return creators[i];
  Rf_error("mxnet_tpu: unknown operator %s", opname);
  return NULL;
}

/* mxr_sym_create_atomic(opname, param_keys, param_vals) */
SEXP mxr_sym_create_atomic(SEXP opname, SEXP keys, SEXP vals) {
  AtomicSymbolCreator target = lookup_creator(CHAR(STRING_ELT(opname, 0)));
  mx_uint np = (mx_uint)Rf_length(keys);
  const char **ck = (const char **)R_alloc(np, sizeof(char *));
  const char **cv = (const char **)R_alloc(np, sizeof(char *));
  for (mx_uint i = 0; i < np; ++i) {
    ck[i] = CHAR(STRING_ELT(keys, i));
    cv[i] = CHAR(STRING_ELT(vals, i));
  }
  SymbolHandle h;
  chk(MXSymbolCreateAtomicSymbol(target, np, ck, cv, &h));
  return wrap_handle(h, symbol_finalizer);
}

/* mxr_sym_compose(sym, name, input_keys, input_syms_list) */
SEXP mxr_sym_compose(SEXP ptr, SEXP name, SEXP keys, SEXP args) {
  mx_uint n = (mx_uint)Rf_length(args);
  int named = Rf_length(keys) > 0;
  if (named && (mx_uint)Rf_length(keys) != n)
    Rf_error("mxnet_tpu: compose keys/args length mismatch");
  SymbolHandle *handles =
      (SymbolHandle *)R_alloc(n, sizeof(SymbolHandle));
  const char **ck = (const char **)R_alloc(n ? n : 1, sizeof(char *));
  for (mx_uint i = 0; i < n; ++i) {
    handles[i] = R_ExternalPtrAddr(VECTOR_ELT(args, i));
    if (named) ck[i] = CHAR(STRING_ELT(keys, i));
  }
  chk(MXSymbolCompose(R_ExternalPtrAddr(ptr), CHAR(STRING_ELT(name, 0)),
                      n, named ? ck : NULL, handles));
  return ptr;
}

/* mxr_sym_infer_shape(sym, keys, ind_ptr, shape_data) ->
 *   list(arg.shapes=list, out.shapes=list, aux.shapes=named list)
 * Uses the Partial variant of the ABI because it also surfaces aux
 * shapes (BatchNorm moving stats) which mx.model needs; complete==0 is
 * an error here, matching the strict MXSymbolInferShape contract. */
SEXP mxr_sym_infer_shape(SEXP ptr, SEXP keys, SEXP ind, SEXP data) {
  mx_uint nk = (mx_uint)Rf_length(keys);
  const char **ck = (const char **)R_alloc(nk ? nk : 1, sizeof(char *));
  mx_uint *cind =
      (mx_uint *)R_alloc(Rf_length(ind) ? Rf_length(ind) : 1,
                         sizeof(mx_uint));
  mx_uint *cdata =
      (mx_uint *)R_alloc(Rf_length(data) ? Rf_length(data) : 1,
                         sizeof(mx_uint));
  for (mx_uint i = 0; i < nk; ++i) ck[i] = CHAR(STRING_ELT(keys, i));
  for (int i = 0; i < Rf_length(ind); ++i)
    cind[i] = (mx_uint)INTEGER(ind)[i];
  for (int i = 0; i < Rf_length(data); ++i)
    cdata[i] = (mx_uint)INTEGER(data)[i];
  mx_uint in_n, out_n, aux_n;
  const mx_uint *in_ndim, *out_ndim, *aux_ndim;
  const mx_uint **in_data, **out_data, **aux_data;
  int complete;
  chk(MXSymbolInferShapePartial(R_ExternalPtrAddr(ptr), nk, ck, cind,
                                cdata, &in_n, &in_ndim, &in_data,
                                &out_n, &out_ndim, &out_data,
                                &aux_n, &aux_ndim, &aux_data, &complete));
  if (!complete)
    Rf_error("mxnet_tpu: infer_shape incomplete (free data shape?)");
  SEXP arg_shapes = PROTECT(Rf_allocVector(VECSXP, in_n));
  for (mx_uint i = 0; i < in_n; ++i) {
    SEXP s = PROTECT(Rf_allocVector(INTSXP, in_ndim[i]));
    for (mx_uint j = 0; j < in_ndim[i]; ++j)
      INTEGER(s)[j] = (int)in_data[i][j];
    SET_VECTOR_ELT(arg_shapes, i, s);
    UNPROTECT(1);
  }
  SEXP out_shapes = PROTECT(Rf_allocVector(VECSXP, out_n));
  for (mx_uint i = 0; i < out_n; ++i) {
    SEXP s = PROTECT(Rf_allocVector(INTSXP, out_ndim[i]));
    for (mx_uint j = 0; j < out_ndim[i]; ++j)
      INTEGER(s)[j] = (int)out_data[i][j];
    SET_VECTOR_ELT(out_shapes, i, s);
    UNPROTECT(1);
  }
  SEXP aux_shapes = PROTECT(Rf_allocVector(VECSXP, aux_n));
  for (mx_uint i = 0; i < aux_n; ++i) {
    SEXP s = PROTECT(Rf_allocVector(INTSXP, aux_ndim[i]));
    for (mx_uint j = 0; j < aux_ndim[i]; ++j)
      INTEGER(s)[j] = (int)aux_data[i][j];
    SET_VECTOR_ELT(aux_shapes, i, s);
    UNPROTECT(1);
  }
  mx_uint aux_name_n;
  const char **aux_names;
  chk(MXSymbolListAuxiliaryStates(R_ExternalPtrAddr(ptr), &aux_name_n,
                                  &aux_names));
  if (aux_name_n == aux_n) {
    SEXP anm = PROTECT(Rf_allocVector(STRSXP, aux_n));
    for (mx_uint i = 0; i < aux_n; ++i)
      SET_STRING_ELT(anm, i, Rf_mkChar(aux_names[i]));
    Rf_setAttrib(aux_shapes, R_NamesSymbol, anm);
    UNPROTECT(1);
  }
  SEXP res = PROTECT(Rf_allocVector(VECSXP, 3));
  SET_VECTOR_ELT(res, 0, arg_shapes);
  SET_VECTOR_ELT(res, 1, out_shapes);
  SET_VECTOR_ELT(res, 2, aux_shapes);
  SEXP nm = PROTECT(Rf_allocVector(STRSXP, 3));
  SET_STRING_ELT(nm, 0, Rf_mkChar("arg.shapes"));
  SET_STRING_ELT(nm, 1, Rf_mkChar("out.shapes"));
  SET_STRING_ELT(nm, 2, Rf_mkChar("aux.shapes"));
  Rf_setAttrib(res, R_NamesSymbol, nm);
  UNPROTECT(5);
  return res;
}

/* ---- Executor --------------------------------------------------------- */

/* mxr_exec_simple_bind(sym, dev_type, dev_id, keys, ind, data,
 *                      for_training) */
SEXP mxr_exec_simple_bind(SEXP sym, SEXP dev_type, SEXP dev_id, SEXP keys,
                          SEXP ind, SEXP data, SEXP for_training) {
  mx_uint nk = (mx_uint)Rf_length(keys);
  const char **ck = (const char **)R_alloc(nk ? nk : 1, sizeof(char *));
  mx_uint *cind =
      (mx_uint *)R_alloc(Rf_length(ind) ? Rf_length(ind) : 1,
                         sizeof(mx_uint));
  mx_uint *cdata =
      (mx_uint *)R_alloc(Rf_length(data) ? Rf_length(data) : 1,
                         sizeof(mx_uint));
  for (mx_uint i = 0; i < nk; ++i) ck[i] = CHAR(STRING_ELT(keys, i));
  for (int i = 0; i < Rf_length(ind); ++i)
    cind[i] = (mx_uint)INTEGER(ind)[i];
  for (int i = 0; i < Rf_length(data); ++i)
    cdata[i] = (mx_uint)INTEGER(data)[i];
  ExecutorHandle h;
  chk(MXExecutorSimpleBind(R_ExternalPtrAddr(sym),
                           Rf_asInteger(dev_type), Rf_asInteger(dev_id),
                           nk, ck, cind, cdata,
                           Rf_asInteger(for_training), &h));
  return wrap_handle(h, executor_finalizer);
}

SEXP mxr_exec_set_arg(SEXP ptr, SEXP name, SEXP values) {
  R_xlen_t n = Rf_xlength(values);
  float *buf = (float *)R_alloc(n, sizeof(float));
  for (R_xlen_t i = 0; i < n; ++i) buf[i] = (float)REAL(values)[i];
  chk(MXExecutorSetArg(R_ExternalPtrAddr(ptr), CHAR(STRING_ELT(name, 0)),
                       buf, (mx_uint)n));
  return R_NilValue;
}

SEXP mxr_exec_forward(SEXP ptr, SEXP is_train) {
  chk(MXExecutorForward(R_ExternalPtrAddr(ptr), Rf_asInteger(is_train)));
  return R_NilValue;
}

SEXP mxr_exec_backward(SEXP ptr) {
  chk(MXExecutorBackward(R_ExternalPtrAddr(ptr)));
  return R_NilValue;
}

SEXP mxr_exec_get_output(SEXP ptr, SEXP index, SEXP size) {
  mx_uint n = (mx_uint)Rf_asInteger(size);
  float *buf = (float *)R_alloc(n, sizeof(float));
  chk(MXExecutorGetOutput(R_ExternalPtrAddr(ptr), Rf_asInteger(index),
                          buf, n));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, n));
  for (mx_uint i = 0; i < n; ++i) REAL(out)[i] = buf[i];
  UNPROTECT(1);
  return out;
}

SEXP mxr_exec_get_grad(SEXP ptr, SEXP name, SEXP size) {
  mx_uint n = (mx_uint)Rf_asInteger(size);
  float *buf = (float *)R_alloc(n, sizeof(float));
  chk(MXExecutorGetGrad(R_ExternalPtrAddr(ptr), CHAR(STRING_ELT(name, 0)),
                        buf, n));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, n));
  for (mx_uint i = 0; i < n; ++i) REAL(out)[i] = buf[i];
  UNPROTECT(1);
  return out;
}

SEXP mxr_exec_set_aux(SEXP ptr, SEXP name, SEXP values) {
  R_xlen_t n = Rf_xlength(values);
  float *buf = (float *)R_alloc(n, sizeof(float));
  for (R_xlen_t i = 0; i < n; ++i) buf[i] = (float)REAL(values)[i];
  chk(MXExecutorSetAux(R_ExternalPtrAddr(ptr), CHAR(STRING_ELT(name, 0)),
                       buf, (mx_uint)n));
  return R_NilValue;
}

SEXP mxr_exec_get_aux(SEXP ptr, SEXP name, SEXP size) {
  mx_uint n = (mx_uint)Rf_asInteger(size);
  float *buf = (float *)R_alloc(n, sizeof(float));
  chk(MXExecutorGetAux(R_ExternalPtrAddr(ptr), CHAR(STRING_ELT(name, 0)),
                       buf, n));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, n));
  for (mx_uint i = 0; i < n; ++i) REAL(out)[i] = buf[i];
  UNPROTECT(1);
  return out;
}

/* ---- Round-2 surface: symbol grad/file IO, optimizer, seed ------------ */

static void optimizer_finalizer(SEXP ptr) {
  OptimizerHandle h = R_ExternalPtrAddr(ptr);
  if (h) { MXOptimizerFree(h); R_ClearExternalPtr(ptr); }
}

/* mxr_sym_grad(extptr, wrt_charvec) -> extptr */
SEXP mxr_sym_grad(SEXP ptr, SEXP wrt) {
  mx_uint n = (mx_uint)Rf_length(wrt);
  const char **names = (const char **)R_alloc(n, sizeof(char *));
  for (mx_uint i = 0; i < n; ++i)
    names[i] = CHAR(STRING_ELT(wrt, i));
  SymbolHandle out;
  chk(MXSymbolGrad(R_ExternalPtrAddr(ptr), n, names, &out));
  return wrap_handle(out, symbol_finalizer);
}

/* mxr_sym_save_file(extptr, path) */
SEXP mxr_sym_save_file(SEXP ptr, SEXP path) {
  chk(MXSymbolSaveToFile(R_ExternalPtrAddr(ptr),
                         CHAR(STRING_ELT(path, 0))));
  return R_NilValue;
}

/* mxr_sym_from_file(path) -> extptr */
SEXP mxr_sym_from_file(SEXP path) {
  SymbolHandle h;
  chk(MXSymbolCreateFromFile(CHAR(STRING_ELT(path, 0)), &h));
  return wrap_handle(h, symbol_finalizer);
}

/* mxr_sym_print(extptr) -> character */
SEXP mxr_sym_print(SEXP ptr) {
  const char *s;
  chk(MXSymbolPrint(R_ExternalPtrAddr(ptr), &s));
  return Rf_mkString(s);
}

/* mxr_opt_create(name, keys_charvec, vals_charvec) -> extptr */
SEXP mxr_opt_create(SEXP name, SEXP keys, SEXP vals) {
  OptimizerCreator creator;
  chk(MXOptimizerFindCreator(CHAR(STRING_ELT(name, 0)), &creator));
  mx_uint n = (mx_uint)Rf_length(keys);
  const char **ck = (const char **)R_alloc(n, sizeof(char *));
  const char **cv = (const char **)R_alloc(n, sizeof(char *));
  for (mx_uint i = 0; i < n; ++i) {
    ck[i] = CHAR(STRING_ELT(keys, i));
    cv[i] = CHAR(STRING_ELT(vals, i));
  }
  OptimizerHandle h;
  chk(MXOptimizerCreateOptimizer(creator, n, ck, cv, &h));
  return wrap_handle(h, optimizer_finalizer);
}

/* mxr_opt_update(opt, index, weight_nd, grad_nd, lr, wd) */
SEXP mxr_opt_update(SEXP opt, SEXP index, SEXP weight, SEXP grad, SEXP lr,
                    SEXP wd) {
  chk(MXOptimizerUpdate(R_ExternalPtrAddr(opt), Rf_asInteger(index),
                        R_ExternalPtrAddr(weight), R_ExternalPtrAddr(grad),
                        (mx_float)Rf_asReal(lr), (mx_float)Rf_asReal(wd)));
  return R_NilValue;
}

/* mxr_random_seed(seed) */
SEXP mxr_random_seed(SEXP seed) {
  chk(MXRandomSeed(Rf_asInteger(seed)));
  return R_NilValue;
}

/* ---- Round-4 surface: imperative NDArray functions -------------------- */

/* mxr_nd_context(extptr) -> c(dev_type, dev_id) */
SEXP mxr_nd_context(SEXP ptr) {
  int dev_type = 1, dev_id = 0;
  chk(MXNDArrayGetContext(R_ExternalPtrAddr(ptr), &dev_type, &dev_id));
  SEXP out = PROTECT(Rf_allocVector(INTSXP, 2));
  INTEGER(out)[0] = dev_type;
  INTEGER(out)[1] = dev_id;
  UNPROTECT(1);
  return out;
}

/* mxr_func_invoke(name, list_of_nd_extptr, scalars_numeric, out_extptr)
 * — registered fixed-arity function; result written into out
 * (reference R-package/src/ndarray.cc dispatched mx.nd.internal ops
 * through MXFuncInvoke the same way). */
SEXP mxr_func_invoke(SEXP name, SEXP use, SEXP scalars, SEXP out) {
  FunctionHandle fun;
  chk(MXGetFunction(CHAR(STRING_ELT(name, 0)), &fun));
  mx_uint nu = (mx_uint)Rf_length(use);
  NDArrayHandle *uh =
      (NDArrayHandle *)R_alloc(nu ? nu : 1, sizeof(NDArrayHandle));
  for (mx_uint i = 0; i < nu; ++i)
    uh[i] = R_ExternalPtrAddr(VECTOR_ELT(use, i));
  mx_uint ns = (mx_uint)Rf_length(scalars);
  mx_float *sc = (mx_float *)R_alloc(ns ? ns : 1, sizeof(mx_float));
  for (mx_uint i = 0; i < ns; ++i) sc[i] = (mx_float)REAL(scalars)[i];
  mx_uint want_u = 0, want_s = 0, want_m = 0;
  int mask = 0;
  chk(MXFuncDescribe(fun, &want_u, &want_s, &want_m, &mask));
  if (want_u != nu || want_s != ns)
    Rf_error("mxnet_tpu: %s expects %u arrays + %u scalars (got %u + %u)",
             CHAR(STRING_ELT(name, 0)), want_u, want_s, nu, ns);
  NDArrayHandle mutate[1] = {R_ExternalPtrAddr(out)};
  chk(MXFuncInvoke(fun, uh, sc, mutate));
  return out;
}

/* ---- Round-4 surface: multi-output symbols (RNN tier) ----------------- */

/* mxr_sym_get_output(extptr, index0) -> extptr (one output as a symbol,
 * the [[ operator on multi-output symbols — reference symbol.cc
 * Symbol::GetOutput) */
SEXP mxr_sym_get_output(SEXP ptr, SEXP index) {
  SymbolHandle out;
  chk(MXSymbolGetOutput(R_ExternalPtrAddr(ptr),
                        (mx_uint)Rf_asInteger(index), &out));
  return wrap_handle(out, symbol_finalizer);
}

/* mxr_sym_group(list_of_extptr) -> extptr (mx.symbol.Group) */
SEXP mxr_sym_group(SEXP handles) {
  mx_uint n = (mx_uint)Rf_length(handles);
  SymbolHandle *hs = (SymbolHandle *)R_alloc(n, sizeof(SymbolHandle));
  for (mx_uint i = 0; i < n; ++i)
    hs[i] = R_ExternalPtrAddr(VECTOR_ELT(handles, i));
  SymbolHandle out;
  chk(MXSymbolCreateGroup(n, hs, &out));
  return wrap_handle(out, symbol_finalizer);
}

/* ---- data iterators ---------------------------------------------------
 * Parity target: the reference's generated R io creators
 * (R-package/R/mxnet_generated.R:480-610 — mx.io.ImageRecordIter,
 * mx.io.MNISTIter, mx.io.CSVIter over MXDataIterCreateIter). Handles
 * returned by MXDataIterGetData/GetLabel are views owned by the
 * iterator, so the values are copied straight into R arrays here and
 * never wrapped with a freeing finalizer. */

static void dataiter_finalizer(SEXP ptr) {
  DataIterHandle h = R_ExternalPtrAddr(ptr);
  if (h) { MXDataIterFree(h); R_ClearExternalPtr(ptr); }
}

/* mxr_io_create(name, keys, vals) -> extptr */
SEXP mxr_io_create(SEXP name, SEXP keys, SEXP vals) {
  mx_uint n;
  DataIterCreator *creators;
  chk(MXListDataIters(&n, &creators));
  const char *want = CHAR(STRING_ELT(name, 0));
  DataIterCreator creator = NULL;
  for (mx_uint i = 0; i < n && !creator; ++i) {
    const char *inm, *desc;
    chk(MXDataIterGetIterInfo(creators[i], &inm, &desc));
    if (strcmp(inm, want) == 0) creator = creators[i];
  }
  if (!creator) Rf_error("mxnet_tpu: unknown data iterator '%s'", want);
  mx_uint np = (mx_uint)Rf_length(keys);
  const char **ck = (const char **)R_alloc(np ? np : 1, sizeof(char *));
  const char **cv = (const char **)R_alloc(np ? np : 1, sizeof(char *));
  for (mx_uint i = 0; i < np; ++i) {
    ck[i] = CHAR(STRING_ELT(keys, i));
    cv[i] = CHAR(STRING_ELT(vals, i));
  }
  DataIterHandle h;
  chk(MXDataIterCreateIter(creator, np, ck, cv, &h));
  return wrap_handle(h, dataiter_finalizer);
}

SEXP mxr_io_before_first(SEXP it) {
  chk(MXDataIterBeforeFirst(R_ExternalPtrAddr(it)));
  return R_NilValue;
}

SEXP mxr_io_next(SEXP it) {
  int more;
  chk(MXDataIterNext(R_ExternalPtrAddr(it), &more));
  return Rf_ScalarInteger(more);
}

static SEXP iter_array(NDArrayHandle h) {
  mx_uint ndim;
  const mx_uint *dims;
  chk(MXNDArrayGetShape(h, &ndim, &dims));
  R_xlen_t n = 1;
  for (mx_uint i = 0; i < ndim; ++i) n *= dims[i];
  float *buf = (float *)R_alloc(n, sizeof(float));
  chk(MXNDArraySyncCopyToCPU(h, buf, (mx_uint)n));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, n));
  for (R_xlen_t i = 0; i < n; ++i) REAL(out)[i] = buf[i];
  SEXP dim = PROTECT(Rf_allocVector(INTSXP, ndim));
  for (mx_uint i = 0; i < ndim; ++i) INTEGER(dim)[i] = (int)dims[i];
  Rf_setAttrib(out, Rf_install("mx.dim"), dim);
  UNPROTECT(2);
  return out;
}

/* mxr_io_value(extptr) -> list(data=, label=, pad=) with C-order dims
 * in the mx.dim attribute (R side converts layout, like mxr_nd_get) */
SEXP mxr_io_value(SEXP it) {
  DataIterHandle h = R_ExternalPtrAddr(it);
  NDArrayHandle data, label;
  int pad;
  chk(MXDataIterGetData(h, &data));
  chk(MXDataIterGetLabel(h, &label));
  chk(MXDataIterGetPadNum(h, &pad));
  SEXP out = PROTECT(Rf_allocVector(VECSXP, 3));
  SET_VECTOR_ELT(out, 0, iter_array(data));
  SET_VECTOR_ELT(out, 1, iter_array(label));
  SET_VECTOR_ELT(out, 2, Rf_ScalarInteger(pad));
  SEXP names = PROTECT(Rf_allocVector(STRSXP, 3));
  SET_STRING_ELT(names, 0, Rf_mkChar("data"));
  SET_STRING_ELT(names, 1, Rf_mkChar("label"));
  SET_STRING_ELT(names, 2, Rf_mkChar("pad"));
  Rf_setAttrib(out, R_NamesSymbol, names);
  UNPROTECT(2);
  return out;
}

/* ---- registration ----------------------------------------------------- */

static const R_CallMethodDef call_methods[] = {
  {"mxr_nd_create", (DL_FUNC)&mxr_nd_create, 3},
  {"mxr_nd_set", (DL_FUNC)&mxr_nd_set, 2},
  {"mxr_nd_get", (DL_FUNC)&mxr_nd_get, 1},
  {"mxr_nd_shape", (DL_FUNC)&mxr_nd_shape, 1},
  {"mxr_nd_save", (DL_FUNC)&mxr_nd_save, 2},
  {"mxr_nd_load", (DL_FUNC)&mxr_nd_load, 1},
  {"mxr_sym_from_json", (DL_FUNC)&mxr_sym_from_json, 1},
  {"mxr_sym_to_json", (DL_FUNC)&mxr_sym_to_json, 1},
  {"mxr_sym_variable", (DL_FUNC)&mxr_sym_variable, 1},
  {"mxr_sym_list_arguments", (DL_FUNC)&mxr_sym_list_arguments, 1},
  {"mxr_sym_list_outputs", (DL_FUNC)&mxr_sym_list_outputs, 1},
  {"mxr_sym_list_auxiliary", (DL_FUNC)&mxr_sym_list_auxiliary, 1},
  {"mxr_sym_list_atomic", (DL_FUNC)&mxr_sym_list_atomic, 0},
  {"mxr_sym_create_atomic", (DL_FUNC)&mxr_sym_create_atomic, 3},
  {"mxr_sym_compose", (DL_FUNC)&mxr_sym_compose, 4},
  {"mxr_sym_infer_shape", (DL_FUNC)&mxr_sym_infer_shape, 4},
  {"mxr_exec_simple_bind", (DL_FUNC)&mxr_exec_simple_bind, 7},
  {"mxr_exec_set_arg", (DL_FUNC)&mxr_exec_set_arg, 3},
  {"mxr_exec_forward", (DL_FUNC)&mxr_exec_forward, 2},
  {"mxr_exec_backward", (DL_FUNC)&mxr_exec_backward, 1},
  {"mxr_exec_get_output", (DL_FUNC)&mxr_exec_get_output, 3},
  {"mxr_exec_get_grad", (DL_FUNC)&mxr_exec_get_grad, 3},
  {"mxr_exec_set_aux", (DL_FUNC)&mxr_exec_set_aux, 3},
  {"mxr_exec_get_aux", (DL_FUNC)&mxr_exec_get_aux, 3},
  {"mxr_sym_grad", (DL_FUNC)&mxr_sym_grad, 2},
  {"mxr_sym_save_file", (DL_FUNC)&mxr_sym_save_file, 2},
  {"mxr_sym_from_file", (DL_FUNC)&mxr_sym_from_file, 1},
  {"mxr_sym_print", (DL_FUNC)&mxr_sym_print, 1},
  {"mxr_opt_create", (DL_FUNC)&mxr_opt_create, 3},
  {"mxr_opt_update", (DL_FUNC)&mxr_opt_update, 6},
  {"mxr_random_seed", (DL_FUNC)&mxr_random_seed, 1},
  {"mxr_sym_get_output", (DL_FUNC)&mxr_sym_get_output, 2},
  {"mxr_sym_group", (DL_FUNC)&mxr_sym_group, 1},
  {"mxr_func_invoke", (DL_FUNC)&mxr_func_invoke, 4},
  {"mxr_nd_context", (DL_FUNC)&mxr_nd_context, 1},
  {"mxr_io_create", (DL_FUNC)&mxr_io_create, 3},
  {"mxr_io_before_first", (DL_FUNC)&mxr_io_before_first, 1},
  {"mxr_io_next", (DL_FUNC)&mxr_io_next, 1},
  {"mxr_io_value", (DL_FUNC)&mxr_io_value, 1},
  {NULL, NULL, 0}
};

void R_init_mxnet_tpu(DllInfo *info) {
  R_registerRoutines(info, NULL, call_methods, NULL, NULL);
  R_useDynamicSymbols(info, FALSE);
}
