# Demo: load a Python-trained checkpoint, run inference, take SGD steps —
# the same workflow perl-package/examples/train_step.pl proves in CI.
#
# Usage (with R installed and the package built):
#   make predict && R CMD INSTALL R-package
#   Rscript R-package/demo/train_step.R <prefix> <epoch>
library(mxnet.tpu)

args <- commandArgs(trailingOnly = TRUE)
prefix <- ifelse(length(args) >= 1, args[[1]], "model")
epoch <- ifelse(length(args) >= 2, as.integer(args[[2]]), 1L)

model <- mx.model.load(prefix, epoch)
cat("arguments:", paste(arguments.MXSymbol(model$symbol), collapse = ", "),
    "\n")

# inference on random data
X <- array(rnorm(32 * 6), dim = c(32, 6))
probs <- predict(model, X)
cat("predict: dim", paste(dim(probs), collapse = "x"),
    "row-sums ~1:", all(abs(rowSums(probs) - 1) < 1e-4), "\n")

# one SGD step: bind for training, seed params, step
executor <- mx.simple.bind(model$symbol, mx.cpu(), grad.req = "write",
                           data = dim(X))
params <- lapply(model$arg.params, as.array)
for (name in names(params)) mx.exec.set.arg(executor, name, params[[name]])
mx.exec.set.arg(executor, "data", X)
labels <- sample(0:1, 32, replace = TRUE)
mx.exec.set.arg(executor, "softmax_label", labels)
params <- mx.model.sgd.step(executor, params, learning.rate = 0.05)
cat("sgd step done; first weight delta:",
    max(abs(params[[1]] - as.array(model$arg.params[[1]]))), "\n")
