# Char-RNN language model in R (reference vignette
# R-package/vignettes/CharRnnModel.Rmd): train mx.lstm on character
# sequences, then sample text with the stateful single-step inference
# model. Runs on synthetic text so it works offline.
library(mxnet.tpu)

# ---- toy corpus: repeated alphabet phrases -------------------------
corpus <- paste(rep("the quick brown fox jumps over the lazy dog ", 40),
                collapse = "")
chars <- sort(unique(strsplit(corpus, "")[[1]]))
vocab <- length(chars)
char.to.id <- stats::setNames(seq_along(chars) - 1L, chars)

seq.len <- 16
batch.size <- 8
ids <- char.to.id[strsplit(corpus, "")[[1]]]
n.seq <- (length(ids) - 1) %/% seq.len
X <- matrix(0L, seq.len, n.seq)
Y <- matrix(0L, seq.len, n.seq)
for (s in seq_len(n.seq)) {
  lo <- (s - 1) * seq.len + 1
  X[, s] <- ids[lo:(lo + seq.len - 1)]
  Y[, s] <- ids[(lo + 1):(lo + seq.len)]     # next-char targets
}

# ---- train (reference mx.lstm call shape, CharRnnModel.Rmd) --------
model <- mx.lstm(list(data = X, label = Y),
                 num.lstm.layer = 1,
                 seq.len = seq.len,
                 num.hidden = 32,
                 num.embed = 16,
                 num.label = vocab,
                 batch.size = batch.size,
                 input.size = vocab,
                 num.round = 5,
                 optimizer = "sgd",
                 learning.rate = 0.2)

# ---- sample with the stateful inference model ----------------------
infer <- mx.lstm.inference(num.lstm.layer = 1,
                           input.size = vocab,
                           num.hidden = 32,
                           num.embed = 16,
                           num.label = vocab,
                           batch.size = 1,
                           arg.params = model$arg.params)
seed.char <- "t"
cur <- char.to.id[[seed.char]]
out <- seed.char
new.seq <- TRUE
for (i in 1:40) {
  step <- mx.lstm.forward(infer, cur, new.seq = new.seq)
  infer <- step$model
  new.seq <- FALSE
  probs <- as.numeric(step$prob)
  cur <- which.max(probs) - 1L               # greedy decode
  out <- paste0(out, chars[cur + 1L])
}
cat("sampled:", out, "\n")
