# Train a small convnet from a CIFAR-10 recordio file — the reference's
# R-package recordio workflow (reference R-package/R/mxnet_generated.R
# ImageRecordIter + example/image-classification/train_cifar10.R),
# running on the runtime-backed mx.io.ImageRecordIter binding.
#
# Prepare data with tools/im2rec.py (or download the CIFAR-10 .rec from
# the reference's data/ scripts), then:
#   Rscript train_cifar10_recordio.R cifar10_train.rec

args <- commandArgs(trailingOnly = TRUE)
rec.file <- if (length(args) >= 1) args[[1]] else "cifar10_train.rec"

library(mxnet)

train.iter <- mx.io.ImageRecordIter(
  path.imgrec = rec.file,
  data.shape = c(3, 28, 28),
  batch.size = 128,
  shuffle = TRUE,
  rand.crop = TRUE,
  rand.mirror = TRUE,
  mean.r = 127.5, mean.g = 127.5, mean.b = 127.5,
  scale = 1 / 127.5)

data <- mx.symbol.Variable("data")
conv1 <- mx.symbol.Convolution(data, kernel = c(3, 3), pad = c(1, 1),
                               num_filter = 32, name = "conv1")
act1 <- mx.symbol.Activation(conv1, act_type = "relu")
pool1 <- mx.symbol.Pooling(act1, kernel = c(2, 2), stride = c(2, 2),
                           pool_type = "max")
conv2 <- mx.symbol.Convolution(pool1, kernel = c(3, 3), pad = c(1, 1),
                               num_filter = 64, name = "conv2")
act2 <- mx.symbol.Activation(conv2, act_type = "relu")
pool2 <- mx.symbol.Pooling(act2, kernel = c(2, 2), stride = c(2, 2),
                           pool_type = "max")
flat <- mx.symbol.Flatten(pool2)
fc1 <- mx.symbol.FullyConnected(flat, num_hidden = 128, name = "fc1")
act3 <- mx.symbol.Activation(fc1, act_type = "relu")
fc2 <- mx.symbol.FullyConnected(act3, num_hidden = 10, name = "fc2")
net <- mx.symbol.SoftmaxOutput(fc2, name = "softmax")

model <- mx.model.FeedForward.create(
  net, X = train.iter, ctx = mx.cpu(), num.round = 10,
  learning.rate = 0.05, momentum = 0.9,
  eval.metric = mx.metric.accuracy)

# At TPU consumption rates, per-epoch JPEG decode cannot feed the chip;
# the runtime's decoded-cache iterator (decode once into a uint8 memmap,
# augment on device) is reachable from R through the same registry:
#   cache.iter <- mx.io.create("CachedImageRecordIter",
#     cache.prefix = paste0(rec.file, ".cache"),
#     data.shape = c(3, 28, 28), batch.size = 128,
#     rand.crop = TRUE, rand.mirror = TRUE)
# (build the cache once with python -c
#  "from mxnet_tpu.io_cache import build_decoded_cache; ..." or let
#  train_imagenet.py --use-cache create it.)
