# Train an MLP with the full FeedForward API (reference
# R-package demo scope: mx.mlp on a two-class dataset).
require(mxnet.tpu)

set.seed(42)
n <- 400
X <- cbind(matrix(rnorm(n * 2, -1), ncol = 2),
           matrix(rnorm(n * 2, +1), ncol = 2))  # (2, 2n) colmajor-ish toy
X <- matrix(rnorm(800 * 5), nrow = 800, ncol = 5)
y <- as.numeric(X[, 1] + X[, 2] > 0)

model <- mx.mlp(X, y, hidden_node = 16, out_node = 2,
                num.round = 10, array.batch.size = 64,
                learning.rate = 0.1, momentum = 0.9,
                initializer = mx.init.uniform(0.5),
                eval.metric = mx.metric.accuracy,
                array.layout = "rowmajor")

pred <- predict(model, t(X[1:64, ]))
cat("predicted dim:", dim(pred), "\n")
mx.model.save(model, "mlp_demo", 10)
