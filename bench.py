"""Benchmark: ResNet-50 training throughput (images/sec) on one chip.

Prints ONE JSON line:
  {"metric": "resnet50_train_imgs_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": N, "step_time_ms": N, "tflops_model": N,
   "tflops_xla": N, "mfu_pct": N, "chip": "...", "compute_dtype": "..."}

Baseline: the reference publishes no in-tree ResNet-50 number
(BASELINE.md); the closest per-GPU proxy is ImageNet Inception-BN on
Titan X, batch 128: 1,281,167 img / 10,666 s ~= 120 img/s/GPU
(example/image-classification/README.md:245-253). vs_baseline =
ours / 120.

MFU accounting: tflops_model uses the standard analytic cost (ResNet-50
forward ~= 4.1 GFLOPs/img at 224x224, training ~= 3x forward), the
convention of the "How to Scale Your Model" MFU definition; tflops_xla
uses XLA's own cost analysis of the compiled step (counts every HLO
flop, so it runs higher). mfu_pct = tflops_model / chip bf16 peak.

Set MXNET_TPU_BENCH_TRACE=<dir> to capture a jax profiler trace of the
timed steps (one trace per round for the perf log).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 120.0  # reference TitanX per-GPU Inception-BN proxy

# analytic training cost per image: 4.089 GFLOPs fwd (He et al. tables,
# 224x224) x3 for fwd+bwd
RESNET50_TRAIN_GFLOPS_PER_IMG = 4.089 * 3

# bf16 peak TFLOP/s by device kind substring
CHIP_PEAK_TFLOPS = {
    "v5 lite": 197.0,   # v5e
    "v5litepod": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6 lite": 918.0,   # v6e / Trillium
    "v6e": 918.0,
    "v3": 123.0,
    "v2": 45.0,
}


def _chip_peak(device_kind: str):
    kind = device_kind.lower()
    for key, peak in CHIP_PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return None


def _accelerator_reachable(timeout_s: int = 240) -> bool:
    """Probe the default (accelerator) backend in a subprocess: a wedged
    TPU tunnel makes `import jax` + device init (or, worse, the first
    real dispatch — a half-alive tunnel answers device enumeration but
    never completes a computation) block forever, which would leave the
    driver with no bench line at all. So the probe must EXECUTE a tiny
    jitted computation, not just list devices. The probe child can be
    killed; the parent then falls back to CPU."""
    import subprocess
    import tempfile
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    # no pipes: a wedged backend can leave helper processes holding the
    # child's stdio open, which blocks subprocess.run's pipe drain even
    # after the timeout kill — write the verdict to a file instead
    probe_src = (
        "import jax, jax.numpy as jnp\n"
        "plat = jax.devices()[0].platform\n"
        "val = float(jax.jit(lambda x: (x * 2).sum())(jnp.ones(128)))\n"
        "assert val == 256.0, val\n"
        "open({path!r}, 'w').write(plat)\n")
    with tempfile.NamedTemporaryFile("r", suffix=".probe") as f:
        child = subprocess.Popen(
            [sys.executable, "-c", probe_src.format(path=f.name)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
        try:
            rc = child.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait()
            return False
        platform = f.read().strip()
    return rc == 0 and platform not in ("", "cpu")


def main():
    if not os.environ.get("JAX_PLATFORMS") \
            and not _accelerator_reachable():
        # re-exec in a fresh interpreter: forcing CPU after the platform
        # plugin has loaded does not stick (same recipe as
        # __graft_entry__._dryrun_in_subprocess / tests/conftest.py)
        import subprocess
        sys.stderr.write("bench.py: accelerator unreachable; "
                         "falling back to CPU\n")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        here = os.path.dirname(os.path.abspath(__file__))
        code = ("import sys; sys.path.insert(0, %r); "
                "import jax; jax.config.update('jax_platforms', 'cpu'); "
                "import bench; bench.main()" % here)
        sys.exit(subprocess.call([sys.executable, "-c", code], env=env,
                                 cwd=here))

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # the axon site hook overrides the env at import; re-apply it so
        # JAX_PLATFORMS=cpu runs work off-TPU
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import build_sgd_train_step

    devices = jax.devices()
    on_accel = devices[0].platform != "cpu"
    batch = int(os.environ.get("MXNET_TPU_BENCH_BATCH",
                               256 if on_accel else 8))
    image = 224 if on_accel else 32
    num_classes = 1000 if on_accel else 16
    steps = int(os.environ.get("MXNET_TPU_BENCH_STEPS",
                               20 if on_accel else 2))

    net = models.get_resnet50(num_classes=num_classes,
                              small_input=not on_accel)
    shapes = {"data": (batch, 3, image, image)}
    arg_shapes, _, aux_shapes = net.infer_shape(**shapes)
    arg_names = net.list_arguments()
    rng = np.random.RandomState(0)

    params = {}
    data = {}
    for name, shape in zip(arg_names, arg_shapes):
        if name == "data":
            data[name] = jax.device_put(
                rng.rand(*shape).astype(np.float32), devices[0])
        elif name == "softmax_label":
            data[name] = jax.device_put(
                rng.randint(0, num_classes, shape).astype(np.float32),
                devices[0])
        elif name.endswith("gamma"):
            params[name] = jax.device_put(np.ones(shape, dtype=np.float32),
                                          devices[0])
        else:
            params[name] = jax.device_put(
                (rng.randn(*shape) * 0.05).astype(np.float32), devices[0])
    aux = [jax.device_put(np.ones(s, dtype=np.float32) if "var" in n
                          else np.zeros(s, dtype=np.float32), devices[0])
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)]

    # bf16 activations/matmuls with f32 master weights — the idiomatic
    # TPU precision (MXU native); override with MXNET_TPU_BENCH_DTYPE
    import jax.numpy as jnp
    dtype_name = os.environ.get("MXNET_TPU_BENCH_DTYPE",
                                "bfloat16" if on_accel else "float32")
    compute_dtype = None if dtype_name == "float32" \
        else getattr(jnp, dtype_name)
    step, _ = build_sgd_train_step(net, ["data"], ["softmax_label"],
                                   lr=0.01, compute_dtype=compute_dtype)
    # donate params/aux so XLA reuses their HBM buffers across steps
    jit_step = jax.jit(step, donate_argnums=(0, 2))
    key = jax.random.PRNGKey(0)

    # XLA's own flop count of the compiled whole-graph train step
    xla_flops = 0.0
    try:
        cost = jit_step.lower(params, data, aux, key).compile() \
            .cost_analysis()
        if isinstance(cost, (list, tuple)):   # older jax: one per device
            cost = cost[0] if cost else {}
        xla_flops = float((cost or {}).get("flops", 0.0))
    except Exception:
        pass

    def _force(tree):
        # fetch a scalar: block_until_ready alone can under-synchronize
        # through remote-device transports, inflating throughput
        leaf = next(iter(tree.values())) if isinstance(tree, dict) else tree
        return float(np.asarray(leaf.sum()))

    # warmup / compile (two steps: the donated-buffer steady state)
    outputs, params, aux = jit_step(params, data, aux, key)
    outputs, params, aux = jit_step(params, data, aux,
                                    jax.random.fold_in(key, steps + 1))
    _force(params)

    trace_dir = os.environ.get("MXNET_TPU_BENCH_TRACE")
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    tic = time.time()
    for i in range(steps):
        outputs, params, aux = jit_step(params, data, aux,
                                        jax.random.fold_in(key, i))
    _force(params)
    elapsed = time.time() - tic
    if trace_dir:
        jax.profiler.stop_trace()

    imgs_per_sec = batch * steps / elapsed
    step_ms = elapsed / steps * 1000.0
    tflops_model = imgs_per_sec * RESNET50_TRAIN_GFLOPS_PER_IMG / 1e3 \
        if image == 224 else 0.0
    tflops_xla = xla_flops * steps / elapsed / 1e12
    peak = _chip_peak(getattr(devices[0], "device_kind", "")) \
        if on_accel else None
    result = {
        "metric": "resnet50_train_imgs_per_sec",
        "value": round(imgs_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
        "compute_dtype": dtype_name,
        "batch": batch,
        "step_time_ms": round(step_ms, 2),
        "tflops_model": round(tflops_model, 1),
        "tflops_xla": round(tflops_xla, 1),
        "chip": getattr(devices[0], "device_kind", devices[0].platform),
    }
    if peak and tflops_model:
        result["mfu_pct"] = round(100.0 * tflops_model / peak, 1)
    if peak and tflops_xla:
        result["mfu_pct_xla"] = round(100.0 * tflops_xla / peak, 1)

    # .bench_cache.json is deliberately git-TRACKED: the end-of-round
    # snapshot then preserves the last real on-chip measurement even
    # when the final bench run degrades to CPU (wedged tunnel)
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache.json")
    if on_accel:
        stamped = dict(result, measured_at=time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        try:
            with open(cache, "w") as f:
                json.dump(stamped, f)
        except OSError:
            pass
    else:
        # CPU fallback (accelerator absent or tunnel wedged): label it
        # and carry the last real on-chip measurement so the record
        # doesn't read as a throughput regression
        result["platform"] = "cpu-fallback"
        try:
            with open(cache) as f:
                result["last_accelerator_result"] = json.load(f)
        except (OSError, ValueError):
            pass
    print(json.dumps(result))


if __name__ == "__main__":
    main()
