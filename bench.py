"""Benchmark: ResNet-50 training throughput (images/sec) on one chip.

Prints ONE JSON line:
  {"metric": "resnet50_train_imgs_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": N, "step_time_ms": N, "tflops_model": N,
   "tflops_xla": N, "mfu_pct": N, "chip": "...", "compute_dtype": "..."}

Baseline: the reference publishes no in-tree ResNet-50 number
(BASELINE.md); the closest per-GPU proxy is ImageNet Inception-BN on
Titan X, batch 128: 1,281,167 img / 10,666 s ~= 120 img/s/GPU
(example/image-classification/README.md:245-253). vs_baseline =
ours / 120.

MFU accounting: tflops_model uses the standard analytic cost (ResNet-50
forward ~= 4.1 GFLOPs/img at 224x224, training ~= 3x forward), the
convention of the "How to Scale Your Model" MFU definition; tflops_xla
uses XLA's own cost analysis of the compiled step (counts every HLO
flop, so it runs higher). mfu_pct = tflops_model / chip bf16 peak.

Set MXNET_TPU_BENCH_TRACE=<dir> to capture a jax profiler trace of the
timed steps (one trace per round for the perf log).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 120.0  # reference TitanX per-GPU Inception-BN proxy

# analytic training cost per image: 4.089 GFLOPs fwd (He et al. tables,
# 224x224) x3 for fwd+bwd
RESNET50_TRAIN_GFLOPS_PER_IMG = 4.089 * 3

# bf16 peak TFLOP/s by device kind substring
CHIP_PEAK_TFLOPS = {
    "v5 lite": 197.0,   # v5e
    "v5litepod": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6 lite": 918.0,   # v6e / Trillium
    "v6e": 918.0,
    "v3": 123.0,
    "v2": 45.0,
}


def _chip_peak(device_kind: str):
    kind = device_kind.lower()
    for key, peak in CHIP_PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return None


_ACCEL_PROBE_VERDICT = None


def _accelerator_reachable(timeout_s: int = 240) -> bool:
    """Probe the default (accelerator) backend in a subprocess: a wedged
    TPU tunnel makes `import jax` + device init (or, worse, the first
    real dispatch — a half-alive tunnel answers device enumeration but
    never completes a computation) block forever, which would leave the
    driver with no bench line at all. So the probe must EXECUTE a tiny
    jitted computation, not just list devices. The probe child can be
    killed; the parent then falls back to CPU.

    The verdict is memoized per process: on a CPU-only box the probe
    burns its full timeout before failing, and every caller in one
    pytest run would otherwise pay it again."""
    global _ACCEL_PROBE_VERDICT
    if _ACCEL_PROBE_VERDICT is not None:
        return _ACCEL_PROBE_VERDICT
    _ACCEL_PROBE_VERDICT = _accelerator_probe(timeout_s)
    return _ACCEL_PROBE_VERDICT


def _accelerator_probe(timeout_s):
    import subprocess
    import tempfile
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    # no pipes: a wedged backend can leave helper processes holding the
    # child's stdio open, which blocks subprocess.run's pipe drain even
    # after the timeout kill — write the verdict to a file instead
    probe_src = (
        "import jax, jax.numpy as jnp\n"
        "plat = jax.devices()[0].platform\n"
        "val = float(jax.jit(lambda x: (x * 2).sum())(jnp.ones(128)))\n"
        "assert val == 256.0, val\n"
        "open({path!r}, 'w').write(plat)\n")
    with tempfile.NamedTemporaryFile("r", suffix=".probe") as f:
        child = subprocess.Popen(
            [sys.executable, "-c", probe_src.format(path=f.name)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
        try:
            rc = child.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait()
            return False
        platform = f.read().strip()
    return rc == 0 and platform not in ("", "cpu")


def _run_child(env_overrides, timeout_s):
    """Run the inner bench in a fresh interpreter; return the parsed
    JSON result dict, or None on crash/timeout/unparseable output.

    The child's stdio goes to files, not pipes: a wedged TPU backend
    leaves helper processes holding the child's fds open, which would
    block a pipe drain even after the timeout kill."""
    import subprocess
    import tempfile
    env = dict(os.environ)
    env.update(env_overrides)
    env["MXNET_TPU_BENCH_INNER"] = "1"
    here = os.path.dirname(os.path.abspath(__file__))
    with tempfile.NamedTemporaryFile("r", suffix=".bench.out") as out, \
            tempfile.NamedTemporaryFile("r", suffix=".bench.err") as err:
        with open(out.name, "w") as out_w, open(err.name, "w") as err_w:
            child = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                stdout=out_w, stderr=err_w, env=env, cwd=here)
            try:
                rc = child.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()
                sys.stderr.write(
                    "bench.py: bench child timed out after %ds\n" % timeout_s)
                return None
        errtxt = err.read()
        if errtxt:
            sys.stderr.write(errtxt[-4000:])
        if rc != 0:
            sys.stderr.write("bench.py: bench child exited rc=%d\n" % rc)
            return None
        for line in out.read().splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    pass
    sys.stderr.write("bench.py: bench child printed no JSON line\n")
    return None


def _bench_smoke(procs=4, image=64, num=192, batch=32, seconds=4.0):
    """Input-pipeline-only smoke bench (``--smoke``): single-thread
    decode baseline vs N process workers, entirely host-side — no
    accelerator (or accelerator probe) involved. Prints ONE JSON line
    with ``input_imgs_per_sec`` plus the io.* telemetry of the process
    run so stalls/ring occupancy are inspectable from CI logs."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import tempfile

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    from pipeline_bench import make_synthetic_rec, measure
    from mxnet_tpu import telemetry, tracing

    tmp = tempfile.mkdtemp(prefix="bench_smoke_")
    rec = os.path.join(tmp, "synth.rec")
    make_synthetic_rec(rec, num, image)
    base = measure(rec, image, batch, 1, seconds, True, mode="thread")
    telemetry.enable()
    telemetry.reset()
    # MXNET_TPU_METRICS_PORT set -> live /metrics + /healthz during the
    # measured run (the operator-scrape acceptance path)
    server = tracing.maybe_init()
    rate = measure(rec, image, batch, procs, seconds, True, mode="process")
    snap = telemetry.snapshot().get("io", {})
    result = {"metric": "input_imgs_per_sec", "value": round(rate, 1),
              "unit": "img/s", "procs": procs,
              "thread1_baseline": round(base, 1),
              "speedup_vs_thread1": round(rate / base, 2) if base else 0.0,
              "cpu_count": os.cpu_count(), "image": image,
              "platform": "cpu", "io_telemetry": snap}
    if server is not None:
        result["metrics_port"] = server.port
    try:
        result.update(_smoke_xprof_tier())
    except Exception as e:
        sys.stderr.write("bench.py: smoke xprof tier failed: %s\n" % e)
    try:
        result.update(_smoke_serve_tier())
    except Exception as e:
        sys.stderr.write("bench.py: smoke serve tier failed: %s\n" % e)
    telemetry.disable()
    print(json.dumps(result))
    return result


def main():
    """Orchestrator. Never imports jax itself, so a wedged accelerator
    backend cannot crash or hang the process that owns the one JSON
    perf line the driver records (round-2 postmortem: the probe passed
    against a half-alive tunnel, then backend init crashed the main
    process and the round's perf record was a stack trace)."""
    # the multichip dp-scaling tier: measured imgs/sec + scaling
    # efficiency on 8 simulated devices; child routing below via env
    # graft: env-ok
    if os.environ.get("MXNET_TPU_BENCH_FSDP"):
        return _bench_fsdp()
    # graft: env-ok
    if os.environ.get("MXNET_TPU_BENCH_MULTICHIP"):
        return _bench_multichip()
    if "multichip" in sys.argv[1:]:
        if "--fsdp" in sys.argv[1:]:
            return _fsdp_main()
        return _multichip_main()
    # the serving tier: continuous-batching inference under open-loop
    # Poisson load on the 8-device mesh ("serve" before the generic
    # --smoke check so `bench.py serve --smoke` routes here)
    # graft: env-ok
    if os.environ.get("MXNET_TPU_BENCH_SERVE_TP"):
        return _bench_serve_tp()
    # graft: env-ok
    if os.environ.get("MXNET_TPU_BENCH_SERVE"):
        return _bench_serve()
    if "serve" in sys.argv[1:]:
        if "--tp" in sys.argv[1:]:
            return _serve_tp_main()
        return _serve_main()
    # the autotune tier: the closed-loop kernel/config search on the
    # forced cpu mesh ("autotune" before the generic --smoke check so
    # `bench.py autotune --smoke` routes here)
    # graft: env-ok
    if os.environ.get("MXNET_TPU_BENCH_AUTOTUNE"):
        return _bench_autotune()
    if "autotune" in sys.argv[1:]:
        return _autotune_main()
    # the fleet tier: fault-tolerant routing over replicas — goodput vs
    # replica count, the killed-replica recovery window, and the rolling
    # param-swap purity proof ("fleet" before the generic --smoke check
    # so `bench.py fleet --smoke` routes here)
    # graft: env-ok
    if os.environ.get("MXNET_TPU_BENCH_FLEET"):
        return _bench_fleet()
    if "fleet" in sys.argv[1:]:
        return _fleet_main()
    # the numerics-observability tier: the fused step timed with the
    # numwatch stats pack off vs armed -> NUMWATCH_health.json
    # graft: env-ok
    if os.environ.get("MXNET_TPU_BENCH_NUMWATCH"):
        return _bench_numwatch()
    if "numwatch" in sys.argv[1:]:
        return _numwatch_main()
    if "--smoke" in sys.argv[1:]:
        import argparse

        p = argparse.ArgumentParser()
        p.add_argument("--smoke", action="store_true")
        p.add_argument("--procs", type=int, default=4)
        p.add_argument("--image", type=int, default=64)
        p.add_argument("--num", type=int, default=192)
        p.add_argument("--batch", type=int, default=32)
        p.add_argument("--seconds", type=float, default=4.0)
        a = p.parse_args()
        return _bench_smoke(a.procs, a.image, a.num, a.batch, a.seconds)
    # NOTE: this environment exports JAX_PLATFORMS=axon globally (the
    # tunnel platform), so "env var present" must NOT mean "skip the
    # orchestration" — that was the round-2 failure: the guard saw a
    # truthy JAX_PLATFORMS, ran the bench in-process, and a half-alive
    # tunnel turned the perf record into a stack trace. Only an explicit
    # cpu platform (or the inner-child marker) runs in-process.
    # the orchestrator must not import mxnet_tpu (package import
    # initializes jax; a wedged backend would hang the parent), so these
    # two reads stay on os.environ rather than the env registry
    # graft: env-ok
    if os.environ.get("MXNET_TPU_BENCH_INNER") \
            or os.environ.get("JAX_PLATFORMS") == "cpu":
        return _bench()

    # graft: env-ok
    timeout_s = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", 2400))
    result = None
    if _accelerator_reachable():
        result = _run_child({}, timeout_s)
        if result is None:
            sys.stderr.write("bench.py: accelerator bench failed; "
                             "falling back to CPU\n")
    else:
        sys.stderr.write("bench.py: accelerator unreachable; "
                         "falling back to CPU\n")
    if result is None:
        result = _run_child({"JAX_PLATFORMS": "cpu"},
                            min(timeout_s, 1200))
    if result is None:
        # last-ditch backstop: the record must still parse
        result = {"metric": "resnet50_train_imgs_per_sec", "value": 0.0,
                  "unit": "img/s", "vs_baseline": 0.0,
                  "platform": "bench-failed"}
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    ".bench_cache.json")) as f:
                result["last_accelerator_result"] = json.load(f)
        except (OSError, ValueError):
            pass
    print(json.dumps(result))


def _bench_lstm(compute_dtype, steps, on_accel, key, _force):
    """Words/sec of a PTB-geometry LSTM LM train step: time-major tokens
    -> Embedding -> fused-scan sym.RNN (2x200 lstm) -> vocab softmax,
    fwd+bwd+SGD fused in one jitted computation (reference workload:
    example/rnn/lstm_bucketing.py)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import sym
    from mxnet_tpu.parallel import build_sgd_train_step

    vocab, hidden, layers = 10000, 200, 2
    seq, batch = (35, 32) if on_accel else (8, 4)

    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data=data, input_dim=vocab, output_dim=hidden,
                          name="embed")
    rnn = sym.RNN(data=embed, state=sym.Variable("rnn_state"),
                  state_cell=sym.Variable("rnn_state_cell"),
                  parameters=sym.Variable("rnn_parameters"),
                  state_size=hidden, num_layers=layers, mode="lstm",
                  name="rnn")
    pred = sym.FullyConnected(sym.Reshape(rnn, shape=(-1, hidden)),
                              num_hidden=vocab, name="pred")
    net = sym.SoftmaxOutput(data=sym.Reshape(pred, shape=(seq, -1, vocab)),
                            label=label, preserve_shape=True,
                            name="softmax")

    rng = np.random.RandomState(0)
    shapes = {"data": (seq, batch)}
    arg_shapes, _, _ = net.infer_shape(**shapes)
    params, feed = {}, {}
    for name, shape in zip(net.list_arguments(), arg_shapes):
        if name == "data":
            feed[name] = jnp.asarray(
                rng.randint(0, vocab, shape), jnp.int32)
        elif name == "softmax_label":
            feed[name] = jnp.asarray(
                rng.randint(0, vocab, shape), jnp.float32)
        elif "state" in name:
            params[name] = jnp.zeros(shape, jnp.float32)
        else:
            params[name] = jnp.asarray(rng.randn(*shape) * 0.05,
                                       jnp.float32)
    step, _ = build_sgd_train_step(net, ["data"], ["softmax_label"],
                                   lr=0.1, compute_dtype=compute_dtype)
    jit_step = jax.jit(step, donate_argnums=(0, 2))
    _, params, _ = jit_step(params, feed, [], key)
    _, params, _ = jit_step(params, feed, [],
                            jax.random.fold_in(key, 10_001))
    _force(params)
    tic = time.time()
    for i in range(steps):
        _, params, _ = jit_step(params, feed, [],
                                jax.random.fold_in(key, i))
    _force(params)
    return batch * seq * steps / (time.time() - tic)


def _bench_recordio(jit_step, params, aux, key, batch, image, num_classes,
                    steps, rec_env, _fence, layout="NCHW"):
    """Opt-in end-to-end tier (MXNET_TPU_BENCH_INPUT=1 or =path.rec):
    the same train step fed from ImageRecordIter — recordio decode +
    augment + H2D included — so the pipeline-vs-compute gap is measured,
    not guessed. Returns extra result fields."""
    import tempfile

    import jax
    from mxnet_tpu import io as mio
    from mxnet_tpu import telemetry

    if os.path.isfile(rec_env):
        rec = rec_env
    else:
        here = os.path.dirname(os.path.abspath(__file__))
        sys.path.insert(0, os.path.join(here, "tools"))
        from pipeline_bench import make_synthetic_rec
        tmp = tempfile.mkdtemp(prefix="bench_rec_")
        rec = os.path.join(tmp, "synth.rec")
        make_synthetic_rec(rec, max(2 * batch, 128), image)
    from mxnet_tpu import env as _env

    threads = _env.get("MXNET_TPU_BENCH_THREADS",
                       default=os.cpu_count() or 1) \
        or (os.cpu_count() or 1)
    it = mio.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, image, image), batch_size=batch,
        preprocess_threads=threads, rand_crop=True, rand_mirror=True,
        scale=1.0 / 255.0)

    def batches():
        while True:
            for b in it:
                yield b
            it.reset()

    gen = batches()

    # input-only rate (decode+augment, host side)
    n, tic = 0, time.time()
    while time.time() - tic < 3.0:
        b = next(gen)
        _ = b.data[0].asnumpy().ravel()[0]
        n += batch
    input_rate = n / (time.time() - tic)

    # end-to-end: iterator -> device -> train step (batches arrive NCHW
    # from the iterator; transpose when the winning step is NHWC)
    def _to_layout(arr):
        import jax.numpy as jnp
        return jnp.transpose(arr, (0, 2, 3, 1)) if layout == "NHWC" else arr

    b = next(gen)
    data = {"data": _to_layout(b.data[0]._data.astype(np.float32)),
            "softmax_label": b.label[0]._data.astype(np.float32)}
    _, params, aux = jit_step(params, data, aux, key)
    _fence(params)
    e2e_steps = max(4, steps // 2)
    tic = time.time()
    for i in range(e2e_steps):
        b = next(gen)
        data = {"data": _to_layout(b.data[0]._data.astype(np.float32)),
                "softmax_label": b.label[0]._data.astype(np.float32)}
        _, params, aux = jit_step(params, data, aux,
                                  jax.random.fold_in(key, 1000 + i))
    _fence(params)
    e2e_rate = batch * e2e_steps / (time.time() - tic)
    result = {"input_imgs_per_sec": round(input_rate, 1),
              "e2e_imgs_per_sec": round(e2e_rate, 1),
              "preprocess_threads": threads}

    # cache-fed tier: decode once into a uint8 memmap, crop/mirror/
    # normalize fused on device (io_cache) — the feed path sized to keep
    # the chip busy from ONE host core where per-epoch JPEG decode needs
    # ~28 (docs/performance.md). For a USER-supplied .rec this builds a
    # full decoded copy on disk (ImageNet scale: ~250 GB, hours of
    # decode), so it requires the explicit MXNET_TPU_BENCH_CACHE=1
    # opt-in; the bench's own synthetic rec is always small enough.
    if os.path.isfile(rec_env) \
            and not _env.get("MXNET_TPU_BENCH_CACHE"):
        sys.stderr.write(
            "bench.py: skipping cached e2e tier for user rec %s "
            "(set MXNET_TPU_BENCH_CACHE=1 to decode it into an "
            "on-disk uint8 cache first)\n" % rec)
        return result
    try:
        from mxnet_tpu import io_cache

        prefix = rec + ".cache"
        meta = io_cache.build_decoded_cache(
            rec, prefix, (3, image + 32, image + 32),
            preprocess_threads=threads)
        if meta["num"] < batch:
            # CachedImageRecordIter yields full batches only; fewer
            # records than one batch would make the feed loop spin
            sys.stderr.write(
                "bench.py: cached tier skipped: %d records < batch %d\n"
                % (meta["num"], batch))
            return result
        # device-feed mode: the iterator ships raw uint8 HWC frames
        # (~1/3 the H2D bytes of float32 crops) and crop/mirror/
        # normalize/layout run INSIDE the jitted step below — one XLA
        # dispatch from memmap to updated params
        cit = io_cache.CachedImageRecordIter(
            prefix, (3, image, image), batch, shuffle=True,
            rand_crop=True, rand_mirror=True, scale=1.0 / 255.0,
            device_feed=True, output_layout=layout)

        def cbatches():
            while True:
                try:
                    yield next(cit)
                except StopIteration:
                    cit.reset()

        import jax.numpy as jnp
        nchw = layout != "NHWC"

        def _aug_step(p, a, u8, tops, lefts, mirror, label, k):
            def one(img, t, l):
                return jax.lax.dynamic_slice(img, (t, l, 0),
                                             (image, image, 3))
            crop = jax.vmap(one)(u8, tops, lefts)
            crop = jnp.where(mirror[:, None, None, None],
                             crop[:, :, ::-1], crop)
            x = crop.astype(jnp.float32) * jnp.float32(1.0 / 255.0)
            if nchw:
                x = jnp.transpose(x, (0, 3, 1, 2))
            # nested jit inlines: still exactly one dispatch per batch
            return jit_step(p, {"data": x, "softmax_label": label}, a, k)

        cached_step = jax.jit(_aug_step, donate_argnums=(0, 1))

        def _cstep(b, k):
            aug = b.aug
            return cached_step(
                params, aux, b.data[0]._data,
                np.asarray(aug["tops"], np.int32),
                np.asarray(aug["lefts"], np.int32),
                np.asarray(aug["mirror"], bool),
                b.label[0]._data.astype(np.float32), k)

        cgen = cbatches()
        _, params, aux = _cstep(next(cgen), jax.random.fold_in(key, 2000))
        _fence(params)
        h2d0 = telemetry.peek("ndarray.h2d_bytes") or 0
        tic = time.time()
        for i in range(e2e_steps):
            _, params, aux = _cstep(next(cgen),
                                    jax.random.fold_in(key, 2001 + i))
        _fence(params)
        dt = time.time() - tic
        h2d = (telemetry.peek("ndarray.h2d_bytes") or 0) - h2d0
        result["e2e_cached_imgs_per_sec"] = round(
            batch * e2e_steps / dt, 1)
        # measured uint8 feed bytes vs what float32 crops would move
        f32_bytes = batch * 3 * image * image * 4
        result["e2e_cached_h2d_bytes_per_step"] = h2d // e2e_steps
        result["e2e_cached_h2d_f32_bytes_per_step"] = f32_bytes
        if h2d:
            result["e2e_cached_h2d_ratio"] = round(
                h2d / e2e_steps / float(f32_bytes), 4)
    except Exception as e:
        sys.stderr.write("bench.py: cached e2e tier failed: %s\n" % e)
    return result


def _smoke_xprof_tier(batch=8, nbatches=8):
    """Tiny fused-step train with the xprof registry armed: the smoke
    BENCH record carries ``compile_time_s`` / ``analytic_mfu`` /
    ``peak_hbm_bytes`` plus the per-site compile summaries (op-category
    breakdown included), so a CPU tier-1 run exercises the whole device
    observability plane end to end."""
    from mxnet_tpu import xprof

    os.environ["MXNET_TPU_FUSED_STEP"] = "1"
    xprof.enable()
    xprof.reset()
    hbm = xprof.HbmWatermark()
    t0 = time.time()
    dps = _bench_fused_dispatch(batch=batch, nbatches=nbatches)
    elapsed = time.time() - t0
    hbm.sample()
    xp = xprof.summary()
    last = (xp["sites"].get("fused_step") or {}).get("last") or {}
    compile_s = xp["totals"]["compile_time_s"]
    xp["bench_analysis"] = xprof.analyze(
        last.get("flops"), last.get("bytes_accessed"),
        step_time_s=max(elapsed - compile_s, 1e-9) / nbatches)
    return {"compile_time_s": round(compile_s, 3),
            "analytic_mfu":
                xp["bench_analysis"].get("analytic_mfu_pct") or 0.0,
            "peak_hbm_bytes": int(hbm.peak),
            "dispatches_per_step": dps,
            "xprof": xp}


def _bench_fused_dispatch(batch=8, nbatches=8):
    """XLA dispatches per training batch through Module.fit: ~1.0 when
    the fused train step (MXNET_TPU_FUSED_STEP=1) is active, 3+ on the
    classic forward/backward/update loop. A tiny MLP keeps this a
    dispatch-count probe, not a throughput tier."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    rng = np.random.RandomState(7)
    X = rng.rand(batch * nbatches, 16).astype(np.float32)
    y = rng.randint(0, 4, (batch * nbatches,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    telemetry.enable()
    before = telemetry.peek("step.dispatches") or 0
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    delta = (telemetry.peek("step.dispatches") or 0) - before
    return round(delta / float(nbatches), 2)


def _multichip_tier(dp, per_device_batch=32, dim=128, hidden=256,
                    nbatches=16, epochs=2):
    """One measured dp tier: the sharded fused step (``device_sync``
    kvstore, mean-psum gradient exchange inside the donated jit) driven
    through ``Module.fit`` on ``dp`` simulated devices, weak-scaled
    (global batch = dp x per-device batch). Returns imgs/sec with
    compile time subtracted, the telemetry dispatch count per step, and
    the collective byte fraction from the fused site's HLO op
    breakdown."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry, xprof

    gb = dp * per_device_batch
    rng = np.random.RandomState(11)
    X = rng.rand(gb * nbatches, dim).astype(np.float32)
    y = rng.randint(0, 4, (gb * nbatches,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=gb)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc3")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(dp)])
    telemetry.enable()
    before = telemetry.peek("step.dispatches") or 0
    xprof.enable()
    xprof.reset()
    t0 = time.perf_counter()
    mod.fit(it, num_epoch=epochs, kvstore="device_sync", optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})
    elapsed = time.perf_counter() - t0
    steps = epochs * nbatches
    xp = xprof.summary()
    compile_s = xp["totals"]["compile_time_s"]
    measured = max(elapsed - compile_s, 1e-9)
    dispatches = ((telemetry.peek("step.dispatches") or 0)
                  - before) / float(steps)
    tier = {"dp": dp, "global_batch": gb, "steps": steps,
            "imgs_per_sec": round(steps * gb / measured, 1),
            "step_ms": round(measured / steps * 1e3, 3),
            "compile_time_s": round(compile_s, 3),
            "dispatches_per_step": round(dispatches, 2)}
    bd = (((xp["sites"].get("fused_step") or {}).get("last") or {})
          .get("op_breakdown")) or {}
    c = bd.get("collective")
    if c:
        total_fl = sum(v.get("flops", 0) for v in bd.values())
        total_by = sum(v.get("bytes", 0) for v in bd.values())
        tier["collective"] = {
            "ops": c.get("count", 0),
            "flop_fraction": round(c.get("flops", 0) / total_fl, 4)
            if total_fl else 0.0,
            "byte_fraction": round(c.get("bytes", 0) / total_by, 4)
            if total_by else 0.0}
    return tier


def _bench_multichip():
    """Measured dp-scaling tier (``bench.py multichip``): the sharded
    fused step timed at dp=1,2,4,8 simulated host devices.

    Scaling efficiency is normalized by the host's REAL parallelism:
    ``eff(dp) = rate(dp) / (min(dp, host_cores) * rate(1))``. On actual
    multi-chip hardware every device is its own chip, ``min`` resolves
    to ``dp``, and this is the standard weak-scaling efficiency. On a
    CPU-simulated mesh the forced devices time-slice the host's cores,
    so the ideal aggregate rate is bounded by ``host_cores`` x the
    single-device rate — the ratio then measures what the tier can
    honestly measure there: the throughput retained under GSPMD
    partitioning (sharded feed, in-jit collectives, per-partition
    dispatch), > 1.0 when one sharded dispatch amortizes per-step host
    overhead that dp=1 pays per batch."""
    import jax

    from mxnet_tpu import telemetry

    os.environ["MXNET_TPU_XPROF_OPS"] = "1"
    n_dev = len(jax.devices())
    host_cores = os.cpu_count() or 1
    dps = [d for d in (1, 2, 4, 8) if d <= n_dev]
    # throwaway warmup: the first fit in a process absorbs one-time
    # backend/init cost (~7ms/step on this tier's scale) that would
    # skew whichever dp tier runs first
    _multichip_tier(1, nbatches=4, epochs=1)
    tiers = [_multichip_tier(dp) for dp in dps]
    rate1 = tiers[0]["imgs_per_sec"] or 1e-9
    for t in tiers:
        ideal = min(t["dp"], host_cores) * rate1
        t["scaling_efficiency"] = round(t["imgs_per_sec"] / ideal, 3)
    result = {"metric": "multichip_imgs_per_sec",
              "value": tiers[-1]["imgs_per_sec"], "unit": "img/s",
              "platform": jax.devices()[0].platform,
              "n_devices": n_dev, "host_cores": host_cores,
              "kvstore": "device_sync", "weak_scaling": True,
              "efficiency_normalization":
                  "rate(dp) / (min(dp, host_cores) * rate(1))",
              "tiers": tiers,
              "scaling_efficiency":
                  {str(t["dp"]): t["scaling_efficiency"] for t in tiers},
              "dispatches_per_step":
                  max(t["dispatches_per_step"] for t in tiers),
              "telemetry":
                  {"step": telemetry.snapshot().get("step", {})}}
    coll = tiers[-1].get("collective")
    if coll:
        result["collective"] = coll
    print(json.dumps(result))
    return result


def _multichip_main():
    """Orchestrator for ``bench.py multichip``: run the dp-scaling tier
    in a child interpreter forced onto 8 simulated cpu devices, write
    the record to MULTICHIP_scaling.json, print the one JSON line. Like
    :func:`main` it never imports jax itself."""
    # graft: env-ok
    timeout_s = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", 1800))
    # graft: env-ok
    xla = os.environ.get("XLA_FLAGS", "")
    result = _run_child({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            (xla + " --xla_force_host_platform_device_count=8").strip(),
        "MXNET_TPU_BENCH_MULTICHIP": "1",
    }, timeout_s)
    if result is None:
        result = {"metric": "multichip_imgs_per_sec", "value": 0,
                  "incomplete": "multichip bench child failed/timed out"}
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "MULTICHIP_scaling.json")
    # a prior `--fsdp` run's record rides along: the two tiers share
    # the artifact, and a plain dp-scaling rerun must not drop it
    try:
        with open(out) as f:
            prev = json.load(f)
        if isinstance(prev, dict) and "fsdp" in prev:
            result.setdefault("fsdp", prev["fsdp"])
    except (OSError, ValueError):
        pass
    try:
        with open(out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    print(json.dumps(result))
    return result


def _pack_bytes_per_device(mod):
    """Bytes of params + optimizer state RESIDENT ON DEVICE 0 (summed
    over its shards): the quantity FSDP divides by the fsdp axis size.
    A replicated array contributes its full size (one copy per device);
    an fsdp-sharded one contributes 1/fsdp of it."""
    import jax

    dev0 = jax.devices()[0]

    def on_dev(arr):
        shards = getattr(arr, "addressable_shards", None)
        if shards:
            return sum(int(s.data.nbytes) for s in shards
                       if s.device == dev0)
        return int(getattr(arr, "nbytes", 0))

    ex = mod._exec_group.executor
    total = 0
    for n in mod._param_names:
        if n in ex.arg_dict:
            total += on_dev(ex.arg_dict[n]._data)
    updater = getattr(mod, "_updater", None)
    states = updater.states if updater is not None else {}
    for leaf in jax.tree_util.tree_leaves(states):
        data = getattr(leaf, "_data", None)
        if data is not None:
            total += on_dev(data)
    return total


def _fsdp_tier(fsdp, per_device_batch=32, dim=128, hidden=256,
               nbatches=16, epochs=2):
    """One measured mesh factoring of the SAME model/batch as the
    multichip tier, with momentum SGD so real optimizer state exists to
    shard: ``fsdp<=1`` is the replicated dp-only baseline, ``fsdp>1``
    reshapes the grid into ``(dp, fsdp)`` and the params + momentum
    shard along ``fsdp``. Returns throughput, per-device pack bytes,
    the fused site's per-partition memory_analysis, dispatch count and
    the collective breakdown (with per-opcode sub-buckets)."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry, xprof

    import jax

    n_dev = len(jax.devices())
    # graft: env-ok (child process; the registry re-reads os.environ)
    if fsdp > 1:
        os.environ["MXNET_TPU_MESH_FSDP"] = str(fsdp)
    else:
        os.environ.pop("MXNET_TPU_MESH_FSDP", None)
    try:
        gb = n_dev * per_device_batch
        rng = np.random.RandomState(11)
        X = rng.rand(gb * nbatches, dim).astype(np.float32)
        y = rng.randint(0, 4, (gb * nbatches,)).astype(np.float32)
        it = mx.io.NDArrayIter(X, y, batch_size=gb)
        net = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc2")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc3")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net,
                            context=[mx.cpu(i) for i in range(n_dev)])
        telemetry.enable()
        before = telemetry.peek("step.dispatches") or 0
        xprof.enable()
        xprof.reset()
        t0 = time.perf_counter()
        mod.fit(it, num_epoch=epochs, kvstore="device_sync",
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05,
                                  "momentum": 0.9})
        elapsed = time.perf_counter() - t0
        steps = epochs * nbatches
        xp = xprof.summary()
        compile_s = xp["totals"]["compile_time_s"]
        measured = max(elapsed - compile_s, 1e-9)
        dispatches = ((telemetry.peek("step.dispatches") or 0)
                      - before) / float(steps)
        tier = {"fsdp": fsdp if fsdp > 1 else 1,
                "dp": n_dev // fsdp if fsdp > 1 else n_dev,
                "global_batch": gb, "steps": steps,
                "imgs_per_sec": round(steps * gb / measured, 1),
                "step_ms": round(measured / steps * 1e3, 3),
                "compile_time_s": round(compile_s, 3),
                "dispatches_per_step": round(dispatches, 2),
                "param_opt_bytes_per_device":
                    _pack_bytes_per_device(mod)}
        site = ((xp["sites"].get("fused_step") or {}).get("last")
                or {})
        mem = {k: site.get(k) for k in
               ("argument_bytes", "temp_bytes", "peak_bytes")
               if site.get(k) is not None}
        if mem:
            # memory_analysis is per-partition under SPMD: these are
            # the bytes ONE device holds for the fused executable
            tier["memory_analysis_per_device"] = mem
        bd = site.get("op_breakdown") or {}
        c = bd.get("collective")
        if c:
            total_by = sum(v.get("bytes", 0) for v in bd.values())
            tier["collective"] = {
                "ops": c.get("count", 0),
                "byte_fraction": round(c.get("bytes", 0) / total_by, 4)
                if total_by else 0.0,
                "by_op": {op: dict(v) for op, v in
                          (c.get("by_op") or {}).items()}}
        return tier
    finally:
        os.environ.pop("MXNET_TPU_MESH_FSDP", None)


def _fsdp_parity_probe(fsdp, nbatches=4):
    """Exact-arithmetic witness that the ZeRO exchange is the same
    mean: a linear head on integer data with quarter-integer seed
    weights keeps every product/psum/update a dyadic rational, so the
    dp-only and (dp, fsdp) loss streams and final params must match
    BIT FOR BIT — any rescale or reduce-order bug shows as inequality,
    not as noise."""
    import mxnet_tpu as mx
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.module import Module

    import jax

    n_dev = len(jax.devices())
    batch, dim, hid = n_dev, 4, 8   # 1 row per shard; hid % fsdp == 0

    def run(use_fsdp):
        # graft: env-ok (child process; registry re-reads os.environ)
        if use_fsdp:
            os.environ["MXNET_TPU_MESH_FSDP"] = str(fsdp)
        else:
            os.environ.pop("MXNET_TPU_MESH_FSDP", None)
        try:
            rng = np.random.RandomState(5)
            X = rng.randint(0, 2, (batch * nbatches, dim)) \
                .astype(np.float32)
            # binary labels: with an 8-wide head the mantissa grows
            # ~6 bits/step, so 0..3 labels overflow float32 by step 4
            y = rng.randint(0, 2, (batch * nbatches, hid)) \
                .astype(np.float32)
            net = sym.Variable("data")
            net = sym.FullyConnected(net, num_hidden=hid, name="fc1")
            net = mx.sym.LinearRegressionOutput(net, name="lro")
            arg_shapes, _, _ = net.infer_shape(
                data=(batch, dim), lro_label=(batch, hid))
            prng = np.random.RandomState(9)
            seed = {name: mx.nd.array(
                (prng.randint(-2, 3, shape) * 0.5).astype(np.float32))
                for name, shape in zip(net.list_arguments(),
                                       arg_shapes)
                if name not in ("data", "lro_label")}
            it = mx.io.NDArrayIter(X, y, batch_size=batch,
                                   label_name="lro_label")
            mod = Module(net,
                         context=[mx.cpu(i) for i in range(n_dev)],
                         label_names=("lro_label",))
            stream = []

            def cb(param):
                stream.append(round(dict(
                    param.eval_metric.get_name_value())["mse"], 10))

            mod.fit(it, num_epoch=1, kvstore="device_sync",
                    eval_metric="mse", optimizer="sgd",
                    arg_params=seed, initializer=None,
                    optimizer_params={"learning_rate": 0.5},
                    batch_end_callback=cb)
            args, _ = mod.get_params()
            return stream, {n: a.asnumpy() for n, a in args.items()}
        finally:
            os.environ.pop("MXNET_TPU_MESH_FSDP", None)

    ref_stream, ref_params = run(False)
    sh_stream, sh_params = run(True)
    params_equal = (set(ref_params) == set(sh_params) and all(
        np.array_equal(ref_params[n], sh_params[n])
        for n in ref_params))
    return {"loss_stream_dp": ref_stream,
            "loss_stream_fsdp": sh_stream,
            "loss_stream_equal": ref_stream == sh_stream,
            "params_bit_identical": bool(params_equal)}


def _bench_fsdp():
    """Measured FSDP tier (``bench.py multichip --fsdp``): the same
    8-device mesh factored ``dp=8`` (replicated baseline) vs
    ``dp=2 x fsdp=4`` (params + momentum sharded). The headline metric
    is the per-device params+opt-state byte ratio — ~1/fsdp when every
    array's dim 0 divides — plus the one-dispatch proof, the collective
    op evidence (all-gather/reduce-scatter emitted by GSPMD inside the
    donated jit) and the exact-arithmetic parity witness."""
    import jax

    from mxnet_tpu import telemetry

    os.environ["MXNET_TPU_XPROF_OPS"] = "1"
    n_dev = len(jax.devices())
    fsdp = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    # throwaway warmup (same reason as the multichip tier)
    _fsdp_tier(1, nbatches=4, epochs=1)
    rep = _fsdp_tier(1)
    sh = _fsdp_tier(fsdp)
    ratio = (sh["param_opt_bytes_per_device"]
             / float(rep["param_opt_bytes_per_device"] or 1))
    parity = _fsdp_parity_probe(fsdp)
    result = {"metric": "fsdp_param_bytes_ratio",
              "value": round(ratio, 4), "unit": "ratio",
              "platform": jax.devices()[0].platform,
              "n_devices": n_dev, "fsdp": fsdp,
              "kvstore": "device_sync",
              "param_bytes_ratio": round(ratio, 4),
              "dispatches_per_step": sh["dispatches_per_step"],
              "replicated": rep, "sharded": sh,
              "parity": parity,
              "telemetry":
                  {"step": telemetry.snapshot().get("step", {})}}
    if sh.get("collective"):
        result["collective"] = sh["collective"]
    print(json.dumps(result))
    return result


def _fsdp_main():
    """Orchestrator for ``bench.py multichip --fsdp``: run the FSDP
    tier in a child forced onto 8 simulated cpu devices and MERGE the
    record under the ``fsdp`` key of MULTICHIP_scaling.json (the plain
    multichip record stays whatever the last plain run wrote). Never
    imports jax itself."""
    # graft: env-ok
    timeout_s = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", 1800))
    # graft: env-ok
    xla = os.environ.get("XLA_FLAGS", "")
    result = _run_child({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            (xla + " --xla_force_host_platform_device_count=8").strip(),
        "MXNET_TPU_BENCH_FSDP": "1",
    }, timeout_s)
    if result is None:
        result = {"metric": "fsdp_param_bytes_ratio", "value": 0,
                  "incomplete": "fsdp bench child failed/timed out"}
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "MULTICHIP_scaling.json")
    record = {}
    try:
        with open(out) as f:
            record = json.load(f)
    except (OSError, ValueError):
        record = {}
    record["fsdp"] = result
    try:
        with open(out, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    print(json.dumps(result))
    return result


def _bench_numwatch(batch=8192, dim=256, hidden=256, classes=16,
                    steps=10, warmup=3, reps=10):
    """Measured numerics-observability tier (``bench.py numwatch``):
    the fused train step timed with the numwatch stats pack off vs
    armed on the same MLP, same process. The pack's reductions run
    inside the donated jit, so the armed arm must stay one dispatch per
    step and one trace signature — both are recorded alongside the
    overhead so the gate catches a silent second dispatch, not just a
    slow one.

    Both arms are built up front and their timed windows run as
    adjacent PAIRS with alternating order (base/armed, armed/base, ...);
    the overhead is the MEDIAN of the per-pair deltas over the median
    base window. Sequential phases confound the comparison with host
    drift several times larger than the effect (first-phase allocator
    warmup, cpufreq wander, noisy CI neighbors — observed ±10% between
    back-to-back identical phases on a one-core host, vs the ~1-3%
    being measured): pairing cancels the slow drift, the order flip
    cancels intra-pair bias, the median rejects burst outliers. The
    batch is large so the per-step compute dominates the pack's
    param-sized reductions — the overhead contract is about
    training-scale steps, not toy dispatch latency."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import numwatch, telemetry
    from mxnet_tpu.fused_step import make_fused_step

    os.environ["MXNET_TPU_FUSED_STEP"] = "1"
    telemetry.enable()

    def build(armed):
        if armed:
            os.environ["MXNET_TPU_NUMWATCH"] = "1"
        else:
            os.environ.pop("MXNET_TPU_NUMWATCH", None)
        rng = np.random.RandomState(3)
        X = rng.rand(batch, dim).astype(np.float32)
        y = rng.randint(0, classes, (batch,)).astype(np.float32)
        it = mx.io.NDArrayIter(X, y, batch_size=batch)
        net = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.01})
        fused = make_fused_step(mod, mx.metric.Accuracy())
        it.reset()
        return fused, mx.metric.Accuracy(), next(iter(it))

    def block(fused):
        ex = fused._executor
        name = ex.arg_names[fused._p_arg_idx[0]]
        jax.block_until_ready(ex.arg_dict[name]._data)

    arms = {"base": build(armed=False), "armed": build(armed=True)}
    os.environ.pop("MXNET_TPU_NUMWATCH", None)
    # warmup compiles each arm exactly once; the armed arm must add
    # exactly ONE fresh trace signature on top of the base arm's
    for fused, metric, b in arms.values():
        r_pre = telemetry.peek("step.fused_recompiles") or 0
        for _ in range(warmup):
            fused.step(b, metric)
        block(fused)
    recompiles = (telemetry.peek("step.fused_recompiles") or 0) - r_pre
    windows = {"base": [], "armed": []}
    armed_steps = 0
    armed_dispatches = 0
    for rep in range(reps):
        order = ("base", "armed") if rep % 2 == 0 else ("armed", "base")
        for name in order:
            fused, metric, b = arms[name]
            d_pre = telemetry.peek("step.dispatches") or 0
            t0 = time.perf_counter()
            for _ in range(steps):
                fused.step(b, metric)
            block(fused)
            windows[name].append((time.perf_counter() - t0) / steps * 1e3)
            if name == "armed":
                armed_steps += steps
                armed_dispatches += \
                    (telemetry.peek("step.dispatches") or 0) - d_pre

    def median(xs):
        xs = sorted(xs)
        mid = len(xs) // 2
        return xs[mid] if len(xs) % 2 else (xs[mid - 1] + xs[mid]) / 2.0

    deltas = [a - b for a, b in zip(windows["armed"], windows["base"])]
    base_ms = median(windows["base"])
    armed_ms = base_ms + median(deltas)
    # the honest error bar: spread of the BASE arm against itself over
    # the run — on a shared one-core host this floor is ~+-5%, which is
    # why the gate's tolerance is sized to it (see bench_baselines.json)
    spread = (max(windows["base"]) - min(windows["base"])) / base_ms * 100
    dps = armed_dispatches / float(armed_steps)
    plane = arms["armed"][0]._numwatch
    plane.fetch()
    overhead = (armed_ms - base_ms) / base_ms * 100.0
    result = {"metric": "numwatch_overhead_pct",
              "value": round(overhead, 2), "unit": "%",
              "platform": jax.devices()[0].platform,
              "overhead_pct": round(overhead, 2),
              "overhead_ok": overhead <= 3.0,
              "baseline_step_ms": round(base_ms, 3),
              "armed_step_ms": round(armed_ms, 3),
              "dispatches_per_step": round(dps, 2),
              "fused_recompiles": int(recompiles),
              "base_window_spread_pct": round(spread, 2),
              "steps_timed": steps, "reps": reps, "batch": batch,
              "tensors": plane.tensor_rows(),
              "guard": {"skipped": int(telemetry.peek(
                            "numwatch.skipped_steps") or 0),
                        "rollbacks": int(telemetry.peek(
                            "numwatch.rollbacks") or 0)},
              "provenance": (None if plane.provenance() is None else
                             dict(zip(("name", "kind", "step"),
                                      plane.provenance()))),
              "health_rows": numwatch.health_rows()[-8:]}
    telemetry.disable()
    print(json.dumps(result))
    return result


def _numwatch_main():
    """Orchestrator for ``bench.py numwatch``: run the numerics
    overhead tier in a child interpreter on the cpu platform, write the
    record to NUMWATCH_health.json, print the one JSON line. Like
    :func:`main` it never imports jax itself."""
    # graft: env-ok
    timeout_s = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", 900))
    result = _run_child({
        "JAX_PLATFORMS": "cpu",
        "MXNET_TPU_BENCH_NUMWATCH": "1",
    }, timeout_s)
    if result is None:
        result = {"metric": "numwatch_overhead_pct", "value": 0,
                  "incomplete": "numwatch bench child failed/timed out"}
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "NUMWATCH_health.json")
    try:
        with open(out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    print(json.dumps(result))
    return result


def _serve_main():
    """Orchestrator for ``bench.py serve [--smoke]``: run the serving
    tier in a child interpreter forced onto 8 simulated cpu devices,
    write the record to SERVE_bench.json, print the one JSON line.
    Like :func:`main` it never imports jax itself."""
    # graft: env-ok
    timeout_s = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", 1500))
    # graft: env-ok
    xla = os.environ.get("XLA_FLAGS", "")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            (xla + " --xla_force_host_platform_device_count=8").strip(),
        "MXNET_TPU_BENCH_SERVE": "1",
    }
    if "--smoke" in sys.argv[1:]:
        env["MXNET_TPU_BENCH_SERVE_SMOKE"] = "1"
    if "--lanes" in sys.argv[1:]:
        env["MXNET_TPU_BENCH_SERVE_LANES"] = "1"
    result = _run_child(env, timeout_s)
    if result is None:
        result = {"metric": "serve_goodput_rps", "value": 0,
                  "unit": "req/s",
                  "incomplete": "serve bench child failed/timed out"}
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "SERVE_bench.json")
    try:
        with open(out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    print(json.dumps(result))
    return result


def _serve_tp_main():
    """Orchestrator for ``bench.py serve --tp``: run the tensor-
    parallel serving tier in a child forced onto 8 simulated cpu
    devices and MERGE the record under the ``tp`` key of
    SERVE_bench.json (the plain serving record stays whatever the last
    plain run wrote — the tp arm must never clobber the goodput
    baselines). Never imports jax itself."""
    # graft: env-ok
    timeout_s = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", 1800))
    # graft: env-ok
    xla = os.environ.get("XLA_FLAGS", "")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            (xla + " --xla_force_host_platform_device_count=8").strip(),
        "MXNET_TPU_BENCH_SERVE_TP": "1",
    }
    if "--smoke" in sys.argv[1:]:
        env["MXNET_TPU_BENCH_SERVE_SMOKE"] = "1"
    result = _run_child(env, timeout_s)
    if result is None:
        result = {"metric": "serve_tp_goodput_rps", "value": 0,
                  "unit": "req/s",
                  "incomplete": "serve --tp bench child failed/timed out"}
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "SERVE_bench.json")
    record = {}
    try:
        with open(out) as f:
            record = json.load(f)
    except (OSError, ValueError):
        record = {}
    record["tp"] = result
    try:
        with open(out, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    print(json.dumps(result))
    return result


def _autotune_main():
    """Orchestrator for ``bench.py autotune [--smoke]``: run the
    closed-loop kernel/config search (mxnet_tpu/autotune.py) in a child
    interpreter on the forced cpu backend, write the search summary to
    AUTOTUNE_search.json, print the one JSON line. Like :func:`main` it
    never imports jax itself."""
    # graft: env-ok
    timeout_s = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", 1200))
    env = {"JAX_PLATFORMS": "cpu", "MXNET_TPU_BENCH_AUTOTUNE": "1"}
    # orchestrator side of the budget knob (never imports mxnet_tpu, so
    # the read stays on os.environ): shrink the search for --smoke
    # unless the operator pinned a budget
    # graft: env-ok
    pinned = os.environ.get("MXNET_TPU_AUTOTUNE_BUDGET_S")
    if "--smoke" in sys.argv[1:] and not pinned:
        env["MXNET_TPU_AUTOTUNE_BUDGET_S"] = "30"
    result = _run_child(env, timeout_s)
    if result is None:
        result = {"metric": "autotune_speedup_vs_default", "value": 0,
                  "unit": "x",
                  "incomplete": "autotune bench child failed/timed out"}
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "AUTOTUNE_search.json")
    try:
        with open(out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    print(json.dumps(result))
    return result


def _bench_autotune():
    """The measured autotune tier (inner child, forced-cpu mesh): the
    bounded two-site search — the ``norm_act`` row-tile knob and the
    ``conv_backward`` kernel choice — every candidate compiled through
    the registry, pruned or timed, every row fenced through
    mfu_experiments.validate() into MFU_EXPERIMENTS.jsonl, winners
    persisted to the autotune cache. The summary is the proof the loop
    closes: on the cpu interpreter the non-default norm_act row tile
    wins, so ``non_default_winner`` must be true."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # graft: env-ok (same pre-import reapply as _bench)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from mxnet_tpu import autotune, xprof

    xprof.enable()
    xprof.reset()
    summary = autotune.run_smoke()
    speedups = [r.get("speedup_vs_default") or 0.0
                for r in summary["sites"].values()]
    result = {"metric": "autotune_speedup_vs_default",
              "value": max(speedups) if speedups else 0.0, "unit": "x",
              "chip": summary["chip"],
              "budget_s": summary["budget_s"],
              "candidates": sum(r["candidates"]
                                for r in summary["sites"].values()),
              "pruned_preflight": sum(r["pruned_preflight"]
                                      for r in summary["sites"].values()),
              "pruned_inapplicable": sum(
                  r["pruned_inapplicable"]
                  for r in summary["sites"].values()),
              "non_default_winner": summary["non_default_winner"],
              "rows_written": summary["rows_written"],
              "rows_refused": summary["rows_refused"],
              "sites": summary["sites"],
              "platform": jax.default_backend()}
    print(json.dumps(result))
    return result


def _serve_tier(srv, rate, duration, slo_ms, rng):
    """One open-loop load tier: Poisson arrivals at ``rate`` req/s for
    ``duration`` seconds, submissions never waiting on completions
    (overload shows up as queue growth -> tail latency, exactly like a
    real load balancer feeding a replica). Returns the tier record,
    including the tier's own occupancy delta, queue-depth percentiles
    and where the adaptive-wait controller ended up."""
    sched = srv.scheduler
    occ0 = sched.occupancy_snapshot()
    sched.drain_depth_samples()
    dim = srv._data_shapes[0][1:]
    row = rng.rand(1, *dim).astype(np.float32)
    reqs = []
    t_next = time.perf_counter()
    t_end = t_next + duration
    while t_next < t_end:
        now = time.perf_counter()
        if t_next > now:
            time.sleep(t_next - now)
        reqs.append(srv.submit([row]))
        t_next += rng.exponential(1.0 / rate)
    lat, failures = [], 0
    for r in reqs:
        try:
            r.get(120)
            lat.append(r.latency_ms)
        except Exception:
            failures += 1
    lat.sort()

    def q(p):
        return round(lat[min(len(lat) - 1, int(p * len(lat)))], 2) \
            if lat else None

    ok = sum(1 for v in lat if v <= slo_ms)
    tier = {"offered_rps": rate, "served": len(lat),
            "failures": failures,
            "achieved_rps": round(len(lat) / duration, 1),
            "goodput_rps": round(ok / duration, 1),
            "p50_ms": q(0.50), "p99_ms": q(0.99), "p999_ms": q(0.999)}
    tier["slo_ok"] = bool(lat) and tier["p99_ms"] <= slo_ms \
        and not failures
    occ1 = sched.occupancy_snapshot()
    db = occ1["batches"] - occ0["batches"]
    tier["mean_occupancy"] = round(
        (occ1["occ_sum"] - occ0["occ_sum"]) / db, 4) if db else 0.0
    depth = sched.drain_depth_samples()
    if depth:
        depth.sort()
        tier["queue_depth"] = {
            "p50": depth[len(depth) // 2],
            "p99": depth[min(len(depth) - 1, int(0.99 * len(depth)))],
            "max": depth[-1]}
    tier["adaptive_wait_ms"] = \
        sched.controller_state()["adaptive_wait_ms"]
    return tier


def _serve_lanes_tier(srv, rate, duration, slo_ms, rng):
    """Mixed-workload tier for ``--lanes``: an interactive Poisson
    stream (70% of the offered rate, deadline = SLO) interleaved with
    a batch-lane stream (30%, 4x looser deadline). Per-lane goodput
    counts a request only against its OWN deadline, so the record
    shows the batch lane riding along without starving and the
    interactive lane holding its deadline."""
    from mxnet_tpu import serving

    dim = srv._data_shapes[0][1:]
    row = rng.rand(1, *dim).astype(np.float32)
    lanes = {"interactive": {"rate": rate * 0.7, "deadline_ms": slo_ms},
             "batch": {"rate": rate * 0.3, "deadline_ms": 4 * slo_ms}}
    reqs = {lane: [] for lane in lanes}
    t0 = time.perf_counter()
    t_end = t0 + duration
    nxt = {lane: t0 + rng.exponential(1.0 / cfg["rate"])
           for lane, cfg in lanes.items()}
    while True:
        lane = min(nxt, key=nxt.get)
        if nxt[lane] >= t_end:
            break
        now = time.perf_counter()
        if nxt[lane] > now:
            time.sleep(nxt[lane] - now)
        cfg = lanes[lane]
        reqs[lane].append(srv.submit([row], priority=lane,
                                     deadline_ms=cfg["deadline_ms"]))
        nxt[lane] += rng.exponential(1.0 / cfg["rate"])
    out = {}
    for lane, cfg in lanes.items():
        lat, shed, failures = [], 0, 0
        for r in reqs[lane]:
            try:
                r.get(120)
                lat.append(r.latency_ms)
            except serving.RequestShed:
                shed += 1
            except Exception:
                failures += 1
        lat.sort()

        def q(p):
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 2) \
                if lat else None

        good = sum(1 for v in lat if v <= cfg["deadline_ms"])
        out[lane] = {"offered_rps": round(cfg["rate"], 1),
                     "deadline_ms": cfg["deadline_ms"],
                     "served": len(lat), "shed": shed,
                     "failures": failures,
                     "goodput_rps": round(good / duration, 1),
                     "p50_ms": q(0.50), "p99_ms": q(0.99)}
    return out


def _bench_serve():
    """The measured serving tier (inner child, forced-cpu mesh): a
    dp-sharded MLP served through ``serving.InferenceServer``, every
    bucket rung warmed once (all the compiles steady state will ever
    need), then an ascending open-loop Poisson sweep until the p99 SLO
    breaks. The record is the serving counterpart of
    MULTICHIP_scaling.json: requests/sec, goodput at SLO, tail
    latency, occupancy, the per-request latency decomposition, and the
    zero-steady-state-retrace proof off the xprof registry."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # graft: env-ok (same pre-import reapply as _bench)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import mxnet_tpu as mx
    from mxnet_tpu import serving, telemetry, tracing, xprof

    telemetry.enable()
    tracing.maybe_init()
    xprof.enable()
    xprof.reset()
    # graft: env-ok
    smoke = bool(os.environ.get("MXNET_TPU_BENCH_SERVE_SMOKE"))
    # graft: env-ok
    lanes_sweep = bool(os.environ.get("MXNET_TPU_BENCH_SERVE_LANES"))

    n_dev = len(jax.devices())
    dp = min(8, n_dev)
    dim, classes, hidden = 64, 16, 128
    max_batch = 32 if smoke else 64
    max_wait_ms = 2.0
    slo_ms = 100.0

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(dp)])
    mod.bind(data_shapes=[("data", (max_batch, dim))],
             label_shapes=[("softmax_label", (max_batch,))],
             for_training=False)
    mod.init_params(mx.initializer.Uniform(0.07))
    srv = serving.InferenceServer(mod, top_k=1, max_batch=max_batch,
                                  max_wait_ms=max_wait_ms, slo_ms=slo_ms)
    rng = np.random.RandomState(0)
    try:
        # warm every ladder rung ONCE — after this, steady state must
        # never compile again, whatever batch mix the load produces
        for b in srv.buckets:
            srv._fused([np.zeros((b, dim), np.float32)])
        xp0 = (xprof.summary()["sites"].get("fused_infer")
               or {}).get("compiles", 0)
        rc0 = telemetry.peek("infer.recompiles") or 0
        di0 = telemetry.peek("infer.dispatches") or 0
        ba0 = telemetry.peek("serve.batches") or 0

        rates = [50, 150, 300] if smoke else [25, 50, 100, 200, 400, 800]
        duration = 1.5 if smoke else 4.0
        tiers = []
        for rate in rates:
            tier = _serve_tier(srv, rate, duration, slo_ms, rng)
            tiers.append(tier)
            if not tier["slo_ok"]:
                break

        lanes = None
        if lanes_sweep:
            lanes = _serve_lanes_tier(srv, 150 if smoke else 200,
                                      duration, slo_ms, rng)

        xp1 = (xprof.summary()["sites"].get("fused_infer")
               or {}).get("compiles", 0)
        rc1 = telemetry.peek("infer.recompiles") or 0
        di1 = telemetry.peek("infer.dispatches") or 0
        ba1 = telemetry.peek("serve.batches") or 0
        stats = srv.stats()
        traj = srv.scheduler.wait_trajectory()
        lane_counts = srv.scheduler.lane_stats()
        buckets = list(srv.buckets)
        compiles = srv.compiles
    finally:
        srv.close()

    good = [t for t in tiers if t["slo_ok"]]
    best = good[-1] if good else tiers[-1]
    decomp = {}
    for k in ("queue_ms", "sched_idle_ms", "h2d_ms", "dispatch_ms",
              "d2h_ms", "pad_waste_ms", "request_ms"):
        exp = telemetry.histogram("serve." + k).export()
        if exp.get("count"):
            decomp[k] = {"mean": round(exp["mean"], 3),
                         "p50": round(exp["p50"], 3),
                         "p99": round(exp["p99"], 3)}
    if len(traj) > 64:   # downsample evenly; the full ring lives in
        step = len(traj) / 64.0          # the scheduler, not the JSON
        traj = [traj[int(i * step)] for i in range(64)]
    batches = ba1 - ba0
    result = {
        "metric": "serve_goodput_rps",
        "value": best["goodput_rps"], "unit": "req/s",
        "platform": jax.devices()[0].platform,
        "n_devices": n_dev, "dp": dp,
        "buckets": buckets, "max_batch": max_batch,
        "max_wait_ms": max_wait_ms, "slo_ms": slo_ms,
        "adaptive": stats.get("adaptive", False),
        "adaptive_wait_ms": stats.get("adaptive_wait_ms"),
        "requests_per_sec": best["achieved_rps"],
        "goodput_rps_at_slo": best["goodput_rps"],
        "p50_ms": best["p50_ms"], "p99_ms": best["p99_ms"],
        "p999_ms": best["p999_ms"],
        "mean_batch_occupancy": stats.get("mean_occupancy", 0.0),
        "queue_depth": {k: stats[sk] for k, sk in
                        (("p50", "queue_depth_p50"),
                         ("p99", "queue_depth_p99"),
                         ("max", "queue_depth_max"))
                        if stats.get(sk) is not None},
        "compiles": compiles,
        "steady_state_retraces": (rc1 - rc0) + (xp1 - xp0),
        "zero_steady_state_retraces": rc1 == rc0 and xp1 == xp0,
        "dispatches_per_request_batch":
            round((di1 - di0) / batches, 3) if batches else 0.0,
        "latency_decomposition_ms": decomp,
        "adaptive_wait_trajectory": traj,
        "lane_counts": lane_counts,
        "tiers": tiers, "smoke": smoke,
    }
    if lanes is not None:
        result["lanes"] = lanes
    print(json.dumps(result))
    return result


def _bench_serve_tp():
    """The measured tensor-parallel serving tier (``bench.py serve
    --tp``, inner child on the forced-cpu mesh): the same MLP served
    at ``tp=1`` (dp-replicated baseline) and ``tp=2`` (params
    NamedSharding-split along each param's largest divisible dim,
    activations resharded in-graph). The record carries the
    bigger-than-one-chip evidence: per-device resident param bytes
    (~1/tp of the baseline), the preflight proof against a simulated
    chip limit the full pack cannot fit, the xprof collective bucket
    emitted INSIDE the one non-donated dispatch (dispatches/batch
    stays exactly 1.0, zero steady-state retraces), goodput/p99 under
    Poisson load, and the delta-aware weight-streaming experiment
    (second refresh moves only the one perturbed param)."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # graft: env-ok (same pre-import reapply as _bench)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import mxnet_tpu as mx
    from mxnet_tpu import serving, telemetry, tracing, xprof
    from mxnet_tpu.checkpoint import param_digest

    os.environ["MXNET_TPU_XPROF_OPS"] = "1"
    telemetry.enable()
    tracing.maybe_init()
    xprof.enable()
    xprof.reset()
    # graft: env-ok
    smoke = bool(os.environ.get("MXNET_TPU_BENCH_SERVE_SMOKE"))

    n_dev = len(jax.devices())
    tp = 2 if n_dev % 2 == 0 else 1
    dim, classes, hidden = 64, 16, 128
    max_batch = 32 if smoke else 64
    max_wait_ms = 2.0
    slo_ms = 100.0
    rng = np.random.RandomState(0)

    def build_module():
        net = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net,
                            context=[mx.cpu(i) for i in range(n_dev)])
        mod.bind(data_shapes=[("data", (max_batch, dim))],
                 label_shapes=[("softmax_label", (max_batch,))],
                 for_training=False)
        mod.init_params(mx.initializer.Uniform(0.07))
        return mod

    def dev0_param_bytes(fused):
        """(bytes resident on device 0, total pack bytes) off the
        placed arrays' addressable shards — the same accounting the
        fsdp tier and tests/test_fsdp.py use."""
        dev0 = total = 0
        for v in fused._param_vals:
            total += int(v.nbytes)
            for s in v.addressable_shards:
                if s.device.id == 0:
                    dev0 += int(np.prod(s.data.shape)
                                * s.data.dtype.itemsize)
        return dev0, total

    def run_arm(tp_arm, refresh_probe):
        mod = build_module()
        srv = serving.InferenceServer(mod, top_k=1, max_batch=max_batch,
                                      max_wait_ms=max_wait_ms,
                                      slo_ms=slo_ms, tp=tp_arm)
        try:
            for b in srv.buckets:
                srv._fused([np.zeros((b, dim), np.float32)])
            dev0, total = dev0_param_bytes(srv._fused)
            last = (xprof.summary()["sites"].get("fused_infer")
                    or {}).get("last") or {}
            xp0 = (xprof.summary()["sites"].get("fused_infer")
                   or {}).get("compiles", 0)
            rc0 = telemetry.peek("infer.recompiles") or 0
            di0 = telemetry.peek("infer.dispatches") or 0
            ba0 = telemetry.peek("serve.batches") or 0
            rates = [50, 150] if smoke else [25, 50, 100, 200, 400]
            duration = 1.5 if smoke else 3.0
            tiers = []
            for rate in rates:
                tier = _serve_tier(srv, rate, duration, slo_ms, rng)
                tiers.append(tier)
                if not tier["slo_ok"]:
                    break
            refresh = None
            if refresh_probe:
                refresh = _serve_tp_refresh_probe(srv, mod,
                                                  param_digest)
            xp1 = (xprof.summary()["sites"].get("fused_infer")
                   or {}).get("compiles", 0)
            rc1 = telemetry.peek("infer.recompiles") or 0
            di1 = telemetry.peek("infer.dispatches") or 0
            ba1 = telemetry.peek("serve.batches") or 0
            good = [t for t in tiers if t["slo_ok"]]
            best = good[-1] if good else tiers[-1]
            batches = ba1 - ba0
            bd = last.get("op_breakdown") or {}
            cat_bytes = sum(int(v.get("bytes", 0)) for v in bd.values()
                            if isinstance(v, dict))
            coll = bd.get("collective") or {}
            arm = {"tp": tp_arm,
                   "buckets": list(srv.buckets),
                   "compiles": srv.compiles,
                   "param_bytes_per_device": dev0,
                   "param_bytes_total": total,
                   "goodput_rps": best["goodput_rps"],
                   "p50_ms": best["p50_ms"], "p99_ms": best["p99_ms"],
                   "dispatches_per_request_batch":
                       round((di1 - di0) / batches, 3)
                       if batches else 0.0,
                   "steady_state_retraces": (rc1 - rc0) + (xp1 - xp0),
                   "zero_steady_state_retraces":
                       rc1 == rc0 and xp1 == xp0,
                   "collective": coll,
                   "collective_bytes_fraction":
                       round(coll.get("bytes", 0)
                             / float(cat_bytes), 4) if cat_bytes
                       else 0.0,
                   "tiers": tiers}
            if refresh is not None:
                arm["refresh"] = refresh
            return arm
        finally:
            srv.close()

    base = run_arm(1, refresh_probe=False)
    sharded = run_arm(tp, refresh_probe=True)

    # the bigger-than-one-chip proof: a simulated chip whose HBM holds
    # 75% of the replicated pack — the full pack preflight-refuses,
    # the tp-sharded pack fits with headroom
    limit = int(0.75 * base["param_bytes_per_device"])
    try:
        xprof.preflight_check(base["param_bytes_per_device"], limit,
                              what="replicated param pack")
        oom_msg = None   # pragma: no cover — limit < pack by design
    except Exception as e:   # noqa: BLE001 (MXNetError expected)
        oom_msg = str(e)
    headroom = xprof.preflight_check(
        sharded["param_bytes_per_device"], limit,
        what="tp-sharded param pack")

    ratio = (sharded["param_bytes_per_device"]
             / float(base["param_bytes_per_device"] or 1))
    result = {
        "metric": "serve_tp_goodput_rps",
        "value": sharded["goodput_rps"], "unit": "req/s",
        "platform": jax.devices()[0].platform,
        "n_devices": n_dev, "tp": tp, "dp": n_dev // tp,
        "max_batch": max_batch, "slo_ms": slo_ms,
        "goodput_rps": sharded["goodput_rps"],
        "p50_ms": sharded["p50_ms"], "p99_ms": sharded["p99_ms"],
        "param_bytes_ratio": round(ratio, 4),
        "preflight": {"simulated_limit_bytes": limit,
                      "replicated_refused": oom_msg is not None,
                      "replicated_error": oom_msg,
                      "tp_headroom_bytes": headroom},
        "dispatches_per_request_batch":
            sharded["dispatches_per_request_batch"],
        "zero_steady_state_retraces":
            sharded["zero_steady_state_retraces"],
        "collective": sharded["collective"],
        "collective_bytes_fraction":
            sharded["collective_bytes_fraction"],
        "refresh": sharded.get("refresh"),
        "replicated": base, "sharded": sharded,
        "smoke": smoke,
    }
    print(json.dumps(result))
    return result


def _serve_tp_refresh_probe(srv, mod, param_digest):
    """The delta-aware weight-streaming experiment, run on the live
    (already-warmed) server: refresh once with the full host pack +
    manifest digests (seeds the resident digests — everything moves,
    the ``full_bytes`` denominator), perturb ONE param, refresh again
    — only that param's bytes may cross to the devices. A post-refresh
    dispatch proves the server still serves."""
    args, _ = mod.get_params()
    host = {n: np.asarray(a.asnumpy()) for n, a in args.items()}
    digests = {n: param_digest(v) for n, v in host.items()}
    srv.refresh_params(host_params=host, digests=digests)
    fused = srv._fused
    full_bytes = fused.last_refresh_bytes
    full_ms = fused.last_refresh_ms
    victim = sorted(host)[0]
    host2 = dict(host)
    host2[victim] = host2[victim] + np.float32(0.5)
    digests2 = dict(digests)
    digests2[victim] = param_digest(host2[victim])
    srv.refresh_params(host_params=host2, digests=digests2)
    delta_bytes = fused.last_refresh_bytes
    dim = srv._data_shapes[0][1:]
    srv.submit([np.zeros((1,) + tuple(dim), np.float32)]).get(60)
    return {"full_bytes": full_bytes, "full_ms": round(full_ms, 3),
            "delta_bytes": delta_bytes,
            "delta_ms": round(fused.last_refresh_ms, 3),
            "delta_bytes_ratio":
                round(delta_bytes / float(full_bytes), 4)
                if full_bytes else 0.0,
            "changed_params": fused.last_refresh_changed,
            "skipped_params": fused.last_refresh_skipped,
            "perturbed": victim}


def _smoke_serve_tier(seconds=1.5, rate=80):
    """Mini serving tier for the generic ``--smoke`` record: a tiny
    single-device server under a short Poisson load; the smoke BENCH
    record then carries serving rps/latency next to the io and xprof
    tiers, so CI exercises the batcher end to end."""
    import mxnet_tpu as mx
    from mxnet_tpu import serving

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (16, 24))],
             label_shapes=[("softmax_label", (16,))], for_training=False)
    mod.init_params(mx.initializer.Uniform(0.07))
    srv = serving.InferenceServer(mod, top_k=1, max_batch=16,
                                  max_wait_ms=2.0, slo_ms=250.0)
    rng = np.random.RandomState(1)
    try:
        for b in srv.buckets:
            srv._fused([np.zeros((b, 24), np.float32)])
        tier = _serve_tier(srv, rate, seconds, 250.0, rng)
        stats = srv.stats()
    finally:
        srv.close()
    return {"serve": {"requests_per_sec": tier["achieved_rps"],
                      "p50_ms": tier["p50_ms"], "p99_ms": tier["p99_ms"],
                      "mean_batch_occupancy": stats.get("mean_occupancy"),
                      "compiles": stats.get("compiles"),
                      "buckets": stats.get("buckets")}}


def _fleet_main():
    """Orchestrator for ``bench.py fleet [--smoke]``: run the
    fault-tolerant routing tier in a child interpreter on the forced
    cpu backend, write the record to FLEET_bench.json, print the one
    JSON line. Like :func:`main` it never imports jax itself."""
    # graft: env-ok
    timeout_s = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", 1500))
    env = {"JAX_PLATFORMS": "cpu", "MXNET_TPU_BENCH_FLEET": "1"}
    if "--smoke" in sys.argv[1:]:
        env["MXNET_TPU_BENCH_FLEET_SMOKE"] = "1"
    result = _run_child(env, timeout_s)
    if result is None:
        result = {"metric": "fleet_goodput_rps", "value": 0,
                  "unit": "req/s",
                  "incomplete": "fleet bench child failed/timed out"}
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "FLEET_bench.json")
    try:
        with open(out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    print(json.dumps(result))
    return result


def _fleet_load(router, rate, duration, rng, row):
    """Open-loop Poisson load on the router: submissions never wait on
    completions; each completion is timestamped, so the caller can bin
    goodput over the wall clock (the killed-replica recovery window
    needs the time axis, not just the totals)."""
    import threading as _threading
    lock = _threading.Lock()
    done = []            # (t_done_s_rel, ok, latency_s)
    t0 = time.perf_counter()
    t_next = t0
    t_end = t0 + duration
    futs = []
    while t_next < t_end:
        now = time.perf_counter()
        if t_next > now:
            time.sleep(t_next - now)
        t_sub = time.perf_counter()

        def _cb(f, t_sub=t_sub):
            t = time.perf_counter()
            with lock:
                done.append((t - t0, f.exception() is None, t - t_sub))

        fut = router.submit([row])
        fut.add_done_callback(_cb)
        futs.append(fut)
        t_next += rng.exponential(1.0 / rate)
    for f in futs:
        try:
            f.result(120)
        except Exception:
            pass
    with lock:
        return list(done), t0


def _fleet_phase_stats(done, duration):
    lat = sorted(l for _, ok, l in done if ok)

    def q(p):
        return round(1e3 * lat[min(len(lat) - 1, int(p * len(lat)))], 2) \
            if lat else None

    return {"served": len(lat),
            "errors": sum(1 for _, ok, _ in done if not ok),
            "achieved_rps": round(len(lat) / duration, 1),
            "p50_ms": q(0.50), "p99_ms": q(0.99)}


def _fleet_double_params(srv):
    """The rolling-swap apply_fn: double every packed param of the
    served executor (stands in for 'the trainer delivered new
    weights'); with the exact-arithmetic demo params the old and new
    outputs are bit-distinguishable."""
    fused = srv._fused
    for i in fused._p_idx:
        arr = fused._ex.arg_arrays[i]
        arr._data = arr._data * 2.0


def _round3(v):
    return None if v is None else round(v, 3)


def _fleet_socket_phase(smoke, rng, row):
    """The socket-transport tier: (a) frame codec vs pickle
    serialization cost per MB; (b) socket-vs-pipe per-request overhead
    at equal open-loop load (the perf claim: p99 within 1.5x of the
    pipe baseline); (c) the chaos acceptance over TCP — net_drop +
    net_partition + net_reorder armed inside the framing layer, zero
    client-visible errors, goodput >= 90% of the clean socket run;
    (d) the disaggregated netfeed epoch — a spawned decode host
    streams batches over loopback into a FeedScheduler and the
    feed-stall p99 proves the chip never starved."""
    import pickle

    from mxnet_tpu import faults, fleet, netfeed, netwire, telemetry

    # (a) serialization: zero-copy frames vs pickle, ms per MB
    payload = [rng.randn(256, 1024).astype(np.float32)]   # 1 MiB
    mb = sum(a.nbytes for a in payload) / (1 << 20)
    reps = 20 if smoke else 50

    def _time(fn):
        fn()                                   # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) * 1e3 / reps

    wire_blob = b"".join(bytes(b) for b in
                         netwire.encode_frame("infer", "m", payload))
    pkl_blob = pickle.dumps(payload, protocol=-1)
    ser = {
        "payload_mb": round(mb, 3),
        # encode builds the sendmsg buffer list — header bytes plus
        # borrowed memoryviews, no payload copy ever happens
        "wire_encode_ms_per_mb": round(_time(
            lambda: netwire.encode_frame("infer", "m", payload)) / mb, 4),
        "wire_decode_ms_per_mb": round(_time(
            lambda: netwire.decode_frame(wire_blob)) / mb, 4),
        "pickle_ms_per_mb": round(_time(
            lambda: pickle.dumps(payload, protocol=-1)) / mb, 4),
        "unpickle_ms_per_mb": round(_time(
            lambda: pickle.loads(pkl_blob)) / mb, 4),
    }

    # (b) + (c): pipe baseline, clean socket, chaos socket — the same
    # open-loop Poisson load through each transport. The rate sits
    # well under either backend's capacity: the claim is per-request
    # overhead at equal load, not a saturation race
    rate = 60 if smoke else 80
    duration = 2.0 if smoke else 5.0

    def _router(backend, **kw):
        kw.setdefault("deadline_ms", 20000.0)
        kw.setdefault("attempt_timeout_ms", 2000.0)
        kw.setdefault("retries", 40)
        kw.setdefault("backoff_ms", 2.0)
        kw.setdefault("health_interval_s", 60.0)
        kw.setdefault("hedge", False)
        return fleet.FleetRouter(backend, 1, **kw)

    def _run(backend, **kw):
        # paired arrival schedule: every transport replays the same
        # Poisson draw, so phase ratios compare completion behaviour
        # rather than arrival-count luck (sigma ~ sqrt(rate*duration))
        prng = np.random.RandomState(20170401)
        with _router(backend, **kw) as r:
            for _ in range(8):                 # warm spawn + compile +
                r.infer([row], timeout=120.0)  # connection dials
            done, _ = _fleet_load(r, rate, duration, prng, row)
            wire = None
            for rid in r.replica_ids():
                rep = r._entries[rid].replica
                if hasattr(rep, "wire_stats"):
                    wire = rep.wire_stats()
            out = _fleet_phase_stats(done, duration)
        if wire:
            out["wire"] = wire
        # load is open-loop and every request eventually completes, so
        # total served just echoes the arrival draw; goodput is what
        # finished INSIDE the measurement window — requests parked in
        # fault-retry past the end are the signal chaos should pay for
        out["in_window"] = sum(1 for t, ok, _ in done
                               if ok and t <= duration)
        return out

    pipe = _run(fleet.in_subprocess("mxnet_tpu.fleet:demo_server_factory"))
    clean = _run(fleet.in_socket("mxnet_tpu.fleet:demo_server_factory"))
    faults.configure("net_drop:0.03,net_partition:0.01,net_reorder:0.08",
                     seed=1)
    try:
        chaos = _run(fleet.in_socket("mxnet_tpu.fleet:demo_server_factory"),
                     attempt_timeout_ms=500.0)
        plan = faults._PLAN
        chaos["injected"] = dict(plan.injected) if plan else {}
    finally:
        faults.configure(None)

    overhead = None
    if pipe["p99_ms"] and clean["p99_ms"]:
        overhead = round(clean["p99_ms"] / pipe["p99_ms"], 3)
    goodput_ratio = None
    if clean["in_window"]:
        goodput_ratio = round(chaos["in_window"] / clean["in_window"], 3)

    # (d) netfeed: a real decode host, one epoch through FeedScheduler
    from mxnet_tpu.io_pipeline import FeedScheduler

    netfeed_rec = {"incomplete": "netfeed epoch did not run"}
    proc, host, port = netfeed.serve_subprocess(
        "mxnet_tpu.netfeed:demo_feed_factory")
    it = netfeed.NetFeedIter(host, port)
    try:
        sched = FeedScheduler(it, depth=2)
        first = sched.next()                   # warm device_put
        telemetry.reset()                      # steady-state stalls only
        telemetry.enable()
        n, nbytes = 1, first.data[0].asnumpy().nbytes
        t0 = time.perf_counter()
        for batch in sched:
            n += 1
            nbytes += batch.data[0].asnumpy().nbytes
            time.sleep(0.002)                  # the "training step"
        wall = time.perf_counter() - t0
        sched.close()
        snap = telemetry.snapshot()
        stall = snap.get("io", {}).get("feed_stall_ms") or {}
        netfeed_rec = {
            "batches": n,
            "payload_mb": round(nbytes / (1 << 20), 2),
            "epoch_s": round(wall, 3),
            "goodput_mb_s": round(nbytes / (1 << 20) / wall, 1)
            if wall else None,
            "feed_stall_p50_ms": _round3(stall.get("p50")),
            "feed_stall_p99_ms": _round3(stall.get("p99")),
            "wait_p99_ms": _round3(
                (snap.get("io", {}).get("netfeed_wait_ms")
                 or {}).get("p99")),
        }
    finally:
        it.close(stop_server=True)
        proc.join(10)
        if proc.is_alive():
            proc.kill()
            proc.join(5)

    return {
        "goodput_rps": clean["achieved_rps"],
        "serialization": ser,
        "pipe": pipe, "clean": clean, "chaos": chaos,
        "overhead_p99_x": overhead,
        "chaos_goodput_ratio": goodput_ratio,
        "netfeed": netfeed_rec,
    }


def _bench_fleet():
    """The measured fleet tier (inner child, forced cpu): a FleetRouter
    over in-process ``demo_server_factory`` replicas.

    Four phases: (1) goodput vs replica count under fixed open-loop
    Poisson load; (2) the chaos acceptance — kill a replica mid-load,
    bin completions into 100ms windows, and measure the recovery time
    until goodput is back to >=90% of the pre-kill rate with ZERO
    client-visible errors; (3) the rolling ``refresh_params`` swap
    under load with the ``torn_swap`` fault armed — every response must
    be pure-old or pure-new bits, none failed; (4) the distributed-
    trace acceptance — subprocess replicas with one armed slow, hedged
    requests traced end to end, the merged clock-aligned tree written
    to FLEET_trace.json."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # graft: env-ok (same pre-import reapply as _bench)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from mxnet_tpu import faults, fleet, telemetry

    telemetry.enable()
    # graft: env-ok
    smoke = bool(os.environ.get("MXNET_TPU_BENCH_FLEET_SMOKE"))
    rate = 120 if smoke else 250
    duration = 2.5 if smoke else 6.0
    counts = (1, 2) if smoke else (1, 2, 4)
    rng = np.random.RandomState(0)
    row = (rng.randint(-3, 4, (1, 8))).astype(np.float32)

    def router(n, **kw):
        kw.setdefault("deadline_ms", 20000.0)
        kw.setdefault("attempt_timeout_ms", 2000.0)
        kw.setdefault("retries", 10)
        kw.setdefault("backoff_ms", 2.0)
        kw.setdefault("health_interval_s", 0.02)
        return fleet.FleetRouter(
            fleet.in_process(fleet.demo_server_factory), n, **kw)

    # phase 1: goodput vs replica count
    scaling = []
    for n in counts:
        with router(n) as r:
            (r.infer([row]),)                     # warm the compile
            done, _ = _fleet_load(r, rate, duration, rng, row)
        tier = {"replicas": n, "offered_rps": rate}
        tier.update(_fleet_phase_stats(done, duration))
        scaling.append(tier)

    # phase 2: kill a replica mid-load; recovery window from 100ms bins
    bin_s = 0.1
    r = router(2)
    try:
        r.infer([row])
        kill_after = duration * 0.4
        killer = {}

        def _load_and_kill():
            import threading as _threading

            def _kill():
                time.sleep(kill_after)
                rid = r.replica_ids()[0]
                killer["t"] = time.perf_counter()
                r.kill_replica(rid)

            kt = _threading.Thread(target=_kill, daemon=True)
            kt.start()
            out = _fleet_load(r, rate, duration, rng, row)
            kt.join(10)
            return out

        done, t0 = _load_and_kill()
        chaos_stats = r.stats()
    finally:
        r.close()
    t_kill = killer["t"] - t0
    n_bins = int(duration / bin_s) + 1
    bins = [0] * n_bins
    for t, ok, _ in done:
        if ok and t < duration:
            bins[int(t / bin_s)] += 1
    pre_bins = [b for i, b in enumerate(bins)
                if 0.5 <= i * bin_s and (i + 1) * bin_s <= t_kill]
    pre_rps = (sum(pre_bins) / (len(pre_bins) * bin_s)) if pre_bins \
        else 0.0
    post = [(i, b) for i, b in enumerate(bins) if i * bin_s >= t_kill]
    recovery_ms = None
    for i, b in post:
        if b / bin_s >= 0.9 * pre_rps:
            recovery_ms = round(((i + 1) * bin_s - t_kill) * 1e3, 1)
            break
    window = [b / bin_s for i, b in post[:int(1.0 / bin_s)]]
    chaos = {"offered_rps": rate,
             "pre_kill_goodput_rps": round(pre_rps, 1),
             "kill_window_min_goodput_rps":
                 round(min(window), 1) if window else None,
             "recovery_ms": recovery_ms,
             "recovered_to_90pct": recovery_ms is not None,
             "client_errors": sum(1 for _, ok, _ in done if not ok),
             "replica_crashes":
                 chaos_stats["counters"].get("replica_crashes", 0),
             "respawns": chaos_stats["counters"].get("respawns", 0),
             "retries": chaos_stats["counters"].get("retries", 0),
             "recovered_requests":
                 chaos_stats["counters"].get("recovered_requests", 0)}

    # phase 3: rolling swap under load, torn_swap fault ARMED — the
    # drain must mask the torn window: pure-old or pure-new, never mixed
    faults.configure("torn_swap", slow_ms=20.0)
    try:
        r = router(2, health_interval_s=60.0)
        try:
            (old,) = r.infer([row])
            ref = fleet.InProcReplica("ref", fleet.demo_server_factory)
            try:
                _fleet_double_params(ref._srv)
                ref._srv.refresh_params()
                (new,) = ref.submit([row]).wait(30)
            finally:
                ref.close()
            stop = {"v": False}
            outs, failed = [], [0]

            def _swap_load():
                while not stop["v"]:
                    try:
                        (o,) = r.infer([row])
                        outs.append(o)
                    except Exception:
                        failed[0] += 1

            import threading as _threading
            threads = [_threading.Thread(target=_swap_load, daemon=True)
                       for _ in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.3)
            r.refresh_params(apply_fn=_fleet_double_params,
                             drain_timeout_s=30.0)
            time.sleep(0.3)
            stop["v"] = True
            for t in threads:
                t.join(30)
            n_old = sum(bool(np.array_equal(o, old)) for o in outs)
            n_new = sum(bool(np.array_equal(o, new)) for o in outs)
            swap_stats = r.stats()
        finally:
            r.close()
        plan = faults.active() and faults._PLAN
        swap = {"responses": len(outs), "failed": failed[0],
                "mixed_version": len(outs) - n_old - n_new,
                "old_version": n_old, "new_version": n_new,
                "swaps": swap_stats["counters"].get("param_swaps", 0),
                "torn_injected":
                    plan.injected.get("torn_swap", 0) if plan else 0}
    finally:
        faults.configure(None)

    # phase 4: distributed trace of a hedged request — SUBPROCESS
    # replicas this time (real OS processes beside the router). The
    # first replica spawns with ``slow_replica`` armed through the
    # inherited env, so first attempts stall past the primed p95 and
    # hedge to the cleanly-spawned second replica; the tail sampler
    # must keep the hedged trees, and each must hold the winning AND
    # the abandoned attempt with replica-side spans from two child
    # pids, clock-aligned onto the router's wall clock.
    from mxnet_tpu import dtrace

    os.environ["MXNET_TPU_FAULTS"] = "slow_replica"
    os.environ["MXNET_TPU_FAULT_SLOW_MS"] = "60"
    try:
        r = fleet.FleetRouter(
            fleet.in_subprocess("mxnet_tpu.fleet:demo_server_factory"),
            1, deadline_ms=30000.0, attempt_timeout_ms=5000.0,
            retries=10, backoff_ms=2.0, hedge=True,
            health_interval_s=60.0)
    finally:
        del os.environ["MXNET_TPU_FAULTS"]
        del os.environ["MXNET_TPU_FAULT_SLOW_MS"]
    trace = {"hedged_trace": None, "pids": 0, "nested": False}
    try:
        r.add_replica()          # clean env: the fast hedge target
        # warm both children's one-time compile UNTRACED (session ids
        # walk the hash ring, so a handful covers both replicas)
        for i in range(16):
            r.infer([row], session="warm%d" % i)
        dtrace.enable()
        for _ in range(12):
            with r._rlock:       # pin the hedge trigger at ~p95=4ms
                r._lat.clear()
                r._lat.extend([0.004] * 30)
            r.infer([row])
        time.sleep(0.5)          # let hedge losers' late replies land
        trace.update(dtrace.stats())
        for ent in dtrace.kept_traces():
            if ent["kept"] != "hedge":
                continue
            spans = ent["spans"]
            atts = [s for s in spans if s["name"] == "fleet.attempt"]
            won = [a for a in atts if a["tags"].get("won")]
            lost = [a for a in atts if a["tags"].get("abandoned")]
            if not (won and lost):
                continue

            def _child_pids(att):
                return {s["pid"] for s in spans
                        if s["parent"] == att["span"]
                        and s["pid"] != att["pid"]}

            pids_w, pids_l = _child_pids(won[0]), _child_pids(lost[0])
            if not (pids_w and pids_l):
                continue
            root = next(s for s in spans if s["parent"] == "")
            lo, hi = root["ts"], root["ts"] + root["dur"]
            eps = 0.025
            nested = all(lo - eps <= s["ts"]
                         and s["ts"] + s["dur"] <= hi + eps
                         for s in spans
                         if s["parent"] == won[0]["span"])
            trace.update({
                "hedged_trace": ent["trace_id"],
                "pids": len({root["pid"]} | pids_w | pids_l),
                "nested": nested,
                "spans_in_tree": len(spans)})
            if nested:
                break
        trace_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "FLEET_trace.json")
        trace["events"] = dtrace.write_chrome_trace(trace_path)
    finally:
        r.close()
        dtrace.disable()

    # phase 5: the watchtower — rerun the steady load under obswatch
    # federation and prove the fleet rollup agrees with the client's
    # own measurements, then seed an SLO burn (slow_replica fault) and
    # prove the multi-window burn-rate alert fires before the error
    # budget is spent. Rollups land in the durable .obswatch store and
    # the whole time-series artifact goes to OBS_fleet.json.
    import shutil

    from mxnet_tpu import obswatch

    here = os.path.dirname(os.path.abspath(__file__))
    obs_dir = os.path.join(here, ".obswatch")
    shutil.rmtree(obs_dir, ignore_errors=True)   # one run, one series
    store = obswatch.TimeSeriesStore(obs_dir, seg_records=2048,
                                     seg_keep=4)

    # (a) federation agreement: manual ticks bracket the load so the
    # counter deltas cover exactly the measured window
    with router(2) as r:
        # warm both compiles, timing each call: the router histogram is
        # cumulative, so the client reference must cover the same
        # request population (warmup + load) for a fair p99 comparison
        warm_lats = []
        for i in range(16):
            t_w = time.perf_counter()
            r.infer([row], session="ow%d" % i)
            warm_lats.append(time.perf_counter() - t_w)
        watch = obswatch.ObsWatch(
            r, store=store,
            monitor=obswatch.BurnRateMonitor(
                slo_target=0.99, fast_s=5.0, slow_s=60.0,
                threshold=14.4),
            interval_ms=3600e3)                  # manual ticks only
        try:
            r0 = watch.tick()
            done, _ = _fleet_load(r, rate, duration, rng, row)
            r1 = watch.tick()
        finally:
            watch.close()
    obs_client = _fleet_phase_stats(done, duration)
    fed_goodput = obswatch.goodput(r0, r1)
    fed_fleet = r1.get("fleet") or {}
    fed_p99 = fed_fleet.get("p99_ms")
    ref = sorted(warm_lats + [l for _, ok, l in done if ok])
    client_p99 = round(
        1e3 * ref[min(len(ref) - 1, int(0.99 * len(ref)))], 3) \
        if ref else None

    def _rel_err(measured, reference):
        if measured is None or not reference:
            return None
        return abs(measured - reference) / reference

    goodput_err = _rel_err(fed_goodput, obs_client["achieved_rps"])
    p99_err = _rel_err(fed_p99, client_p99)
    obs = {"fed_goodput_rps": (None if fed_goodput is None
                               else round(fed_goodput, 1)),
           "client_goodput_rps": obs_client["achieved_rps"],
           "goodput_rel_err": (None if goodput_err is None
                               else round(goodput_err, 4)),
           "fed_p50_ms": fed_fleet.get("p50_ms"),
           "fed_p99_ms": fed_p99,
           "fed_p999_ms": fed_fleet.get("p999_ms"),
           "client_p99_ms": client_p99,
           "client_load_p99_ms": obs_client["p99_ms"],
           "p99_rel_err": (None if p99_err is None
                           else round(p99_err, 4)),
           "replicas_up": fed_fleet.get("up"),
           "store_dir": os.path.relpath(obs_dir, here)}

    # (b) seeded SLO burn: one-in-two batches stalls past the SLO, so
    # the fleet burns budget at ~2x sustainable (slo_target=0.75 budget
    # with ~50% bad) — the fast+slow windows must both trip the alert
    # while budget_spent < 1
    faults.configure("slow_replica:0.5", slow_ms=15.0)
    burn = {"alert_fired": False, "alert_at_s": None,
            "budget_spent_at_alert": None, "fast_burn": None,
            "slow_burn": None}
    try:
        def _slo_factory():
            srv = fleet.demo_server_factory()
            srv.scheduler.slo_ms = 10.0          # breached by the fault
            return srv

        burn_rate = 60 if smoke else 120
        fast_s, slow_s = (0.8, 3.2) if smoke else (1.0, 6.0)
        r = fleet.FleetRouter(
            fleet.in_process(_slo_factory), 2, deadline_ms=20000.0,
            attempt_timeout_ms=2000.0, retries=10, backoff_ms=2.0,
            health_interval_s=60.0)
        try:
            for i in range(16):
                r.infer([row], session="bw%d" % i)
            watch = obswatch.ObsWatch(
                r, store=store,
                monitor=obswatch.BurnRateMonitor(
                    slo_target=0.75, fast_s=fast_s, slow_s=slow_s,
                    threshold=1.5, min_events=20),
                interval_ms=100.0)
            try:
                t_burn0 = watch.tick()["ts"]
                watch.start()
                _fleet_load(r, burn_rate, duration, rng, row)
            finally:
                watch.close()
        finally:
            r.close()
        for rec in store.records():
            v = rec.get("burn") or {}
            if v.get("alert") and rec.get("ts", 0.0) >= t_burn0:
                burn.update({
                    "alert_fired": True,
                    "alert_at_s": round(rec["ts"] - t_burn0, 3),
                    "budget_spent_at_alert": v.get("budget_spent"),
                    "fast_burn": v.get("fast_burn"),
                    "slow_burn": v.get("slow_burn")})
                break
    finally:
        faults.configure(None)

    obs_ok = bool(goodput_err is not None and goodput_err <= 0.05
                  and p99_err is not None and p99_err <= 0.05)
    burn_ok = bool(burn["alert_fired"]
                   and burn["budget_spent_at_alert"] is not None
                   and burn["budget_spent_at_alert"] < 1.0)
    obs_art = {
        "metric": "obswatch_fleet_goodput_rps",
        "value": obs["fed_goodput_rps"] or 0, "unit": "req/s",
        "federation": obs, "final_rollup": r1, "burn": burn,
        "series": {name: store.query(name) for name in
                   ("fleet.p99_ms", "fleet.served",
                    "fleet.slo_breaches", "burn.fast_burn",
                    "burn.slow_burn", "burn.budget_spent")},
        "obs_ok": obs_ok, "burn_ok": burn_ok, "smoke": smoke,
    }
    try:
        with open(os.path.join(here, "OBS_fleet.json"), "w") as f:
            json.dump(obs_art, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass

    # phase 6: the socket transport — serialization vs pickle, the
    # socket-vs-pipe overhead claim, chaos over TCP, and the netfeed
    # epoch (the zero-copy wire's whole acceptance record)
    try:
        sock = _fleet_socket_phase(smoke, rng, row)
    except Exception as e:   # noqa: BLE001 (recorded, never fatal)
        sock = {"incomplete": "socket phase failed: %s" % e}
    sock_ok = bool(
        "incomplete" not in sock
        and sock["chaos"]["errors"] == 0
        and (sock["chaos_goodput_ratio"] or 0) >= 0.9
        and (sock["overhead_p99_x"] or 99) <= 1.5)

    best = max(scaling, key=lambda t: t["achieved_rps"])
    result = {
        "metric": "fleet_goodput_rps",
        "value": best["achieved_rps"], "unit": "req/s",
        "platform": jax.devices()[0].platform,
        "replicas_best": best["replicas"],
        "scaling": scaling, "chaos": chaos, "swap": swap,
        "chaos_ok": (chaos["client_errors"] == 0
                     and chaos["recovered_to_90pct"]),
        "swap_ok": (swap["failed"] == 0 and swap["mixed_version"] == 0
                    and swap["torn_injected"] >= 2),
        "trace": trace,
        "trace_ok": (trace["hedged_trace"] is not None
                     and trace["pids"] >= 3 and trace["nested"]),
        "obs": obs, "burn": burn,
        "obs_ok": obs_ok, "burn_ok": burn_ok,
        "socket": sock, "socket_ok": sock_ok,
        "smoke": smoke,
    }
    print(json.dumps(result))
    return result


def _bench():
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # the axon site hook overrides the env at import; re-apply it so
        # JAX_PLATFORMS=cpu runs work off-TPU
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import mxnet_tpu as mx
    from mxnet_tpu import models, telemetry, tracing, xprof
    from mxnet_tpu.parallel import build_sgd_train_step

    telemetry.enable()
    tracing.maybe_init()
    # arm the device observability plane: every step-path compile below
    # lands in the registry, and the BENCH record carries the summary
    xprof.enable()
    xprof.reset()

    devices = jax.devices()
    on_accel = devices[0].platform != "cpu"
    from mxnet_tpu import env as _env

    batch = _env.get("MXNET_TPU_BENCH_BATCH",
                     default=256 if on_accel else 8) \
        or (256 if on_accel else 8)
    image = 224 if on_accel else 32
    num_classes = 1000 if on_accel else 16
    steps = _env.get("MXNET_TPU_BENCH_STEPS",
                     default=20 if on_accel else 2) \
        or (20 if on_accel else 2)

    net = models.get_resnet50(num_classes=num_classes,
                              small_input=not on_accel)
    rng = np.random.RandomState(0)

    def _random_feeds(a_net, data_shape, n_class):
        """Random params/data/aux for a softmax net, placed on the
        bench device — one init rule for every measured tier."""
        a_shapes, _, x_shapes = a_net.infer_shape(data=data_shape)
        p, d = {}, {}
        for name, shape in zip(a_net.list_arguments(), a_shapes):
            if name == "data":
                d[name] = jax.device_put(
                    rng.rand(*shape).astype(np.float32), devices[0])
            elif name == "softmax_label":
                d[name] = jax.device_put(
                    rng.randint(0, n_class, shape).astype(np.float32),
                    devices[0])
            elif name.endswith("gamma"):
                p[name] = jax.device_put(
                    np.ones(shape, dtype=np.float32), devices[0])
            else:
                p[name] = jax.device_put(
                    (rng.randn(*shape) * 0.05).astype(np.float32),
                    devices[0])
        x = [jax.device_put(np.ones(s, dtype=np.float32) if "var" in n
                            else np.zeros(s, dtype=np.float32), devices[0])
             for n, s in zip(a_net.list_auxiliary_states(), x_shapes)]
        return p, d, x

    params, data, aux = _random_feeds(net, (batch, 3, image, image),
                                      num_classes)

    # bf16 activations/matmuls with f32 master weights — the idiomatic
    # TPU precision (MXU native); override with MXNET_TPU_BENCH_DTYPE
    import jax.numpy as jnp
    dtype_name = _env.get("MXNET_TPU_BENCH_DTYPE") \
        or ("bfloat16" if on_accel else "float32")
    compute_dtype = None if dtype_name == "float32" \
        else getattr(jnp, dtype_name)
    step, _ = build_sgd_train_step(net, ["data"], ["softmax_label"],
                                   lr=0.01, compute_dtype=compute_dtype)
    # donate params/aux so XLA reuses their HBM buffers across steps
    jit_step = jax.jit(step, donate_argnums=(0, 2))
    key = jax.random.PRNGKey(0)

    # XLA's own flop count of the compiled whole-graph train step, with
    # the compile wall time, memory analysis and op-category breakdown
    # recorded through the xprof compile registry
    xla_flops = 0.0
    compile_time_s = None
    bench_rec = None
    try:
        tic_c = time.time()
        compiled = jit_step.lower(params, data, aux, key).compile()
        compile_time_s = time.time() - tic_c
        bench_rec = xprof.record_compile("bench.train_step", compiled,
                                         compile_time_s)
        xla_flops = bench_rec.flops or 0.0
    except Exception:
        pass

    def _force(tree):
        # fetch a scalar: block_until_ready alone can under-synchronize
        # through remote-device transports, inflating throughput
        leaf = next(iter(tree.values())) if isinstance(tree, dict) else tree
        return float(np.asarray(leaf.sum()))

    # warmup / compile (two steps: the donated-buffer steady state)
    outputs, params, aux = jit_step(params, data, aux, key)
    outputs, params, aux = jit_step(params, data, aux,
                                    jax.random.fold_in(key, steps + 1))
    _force(params)
    # live-buffer watermark, sampled outside the timed window so the
    # accounting never perturbs the throughput number
    hbm_wm = xprof.HbmWatermark()
    hbm_wm.sample()

    trace_dir = _env.get("MXNET_TPU_BENCH_TRACE")
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    tic = time.time()
    t_last = time.perf_counter()
    for i in range(steps):
        with telemetry.span("bench.step"):
            outputs, params, aux = jit_step(params, data, aux,
                                            jax.random.fold_in(key, i))
        now = time.perf_counter()
        tracing.record_step((now - t_last) * 1e3)
        t_last = now
    _force(params)
    elapsed = time.time() - tic
    if trace_dir:
        jax.profiler.stop_trace()

    imgs_per_sec = batch * steps / elapsed
    layout = "NCHW"
    nhwc_rate = None
    cifar_rate = None
    # MXNET_TPU_BENCH_FORCE_EXPERIMENTS=1 exercises the accelerator-only
    # experiment paths on CPU so CI covers the code that will run the
    # moment a chip answers
    run_experiments = on_accel \
        or _env.get("MXNET_TPU_BENCH_FORCE_EXPERIMENTS")
    if run_experiments:
        # round-3 measured experiment, run opportunistically whenever a
        # real chip answers: time the SAME step with the channels-last
        # tower (weights are OIHW in both layouts so params carry over)
        # and let the faster layout own the headline number.
        try:
            net2 = models.get_resnet50(num_classes=num_classes,
                                       small_input=not on_accel,
                                       layout="NHWC")
            step2, _ = build_sgd_train_step(
                net2, ["data"], ["softmax_label"], lr=0.01,
                compute_dtype=compute_dtype)
            jit2 = jax.jit(step2, donate_argnums=(0, 2))
            data2 = dict(data)
            data2["data"] = jnp.transpose(data["data"], (0, 2, 3, 1))
            # donate COPIES: the first jit2 call must not consume the
            # baseline's params/aux buffers — the losing-NHWC path (and
            # the recordio tier) keeps using them
            p2 = {k: jnp.copy(v) for k, v in params.items()}
            a2 = [jnp.copy(v) for v in aux]
            _, p2, a2 = jit2(p2, data2, a2, key)
            _, p2, a2 = jit2(p2, data2, a2,
                             jax.random.fold_in(key, steps + 2))
            _force(p2)
            tic2 = time.time()
            for i in range(steps):
                _, p2, a2 = jit2(p2, data2, a2,
                                 jax.random.fold_in(key, i))
            _force(p2)
            nhwc_rate = batch * steps / (time.time() - tic2)
            if nhwc_rate > imgs_per_sec:
                layout = "NHWC"
                imgs_per_sec = nhwc_rate
                elapsed = batch * steps / nhwc_rate
                params, aux, data = p2, a2, data2
                jit_step = jit2
        except Exception as e:  # the experiment must never cost the record
            sys.stderr.write("bench.py: NHWC variant failed: %s\n" % e)

        # CIFAR-10 Inception-BN-28-small: the reference's PUBLISHED
        # headline (842 img/s on one GTX 980, batch 128 —
        # example/image-classification/README.md:202-206), measured with
        # the same protocol so vs_baseline_cifar is apples-to-apples
        # against the reference's own number.
        try:
            cnet = models.get_inception_bn_28_small(num_classes=10)
            cbatch = 128 if on_accel else 4
            cparams, cdata, caux = _random_feeds(cnet,
                                                 (cbatch, 3, 28, 28), 10)
            cstep, _ = build_sgd_train_step(
                cnet, ["data"], ["softmax_label"], lr=0.01,
                compute_dtype=compute_dtype)
            cjit = jax.jit(cstep, donate_argnums=(0, 2))
            _, cparams, caux = cjit(cparams, cdata, caux, key)
            _, cparams, caux = cjit(cparams, cdata, caux,
                                    jax.random.fold_in(key, steps + 3))
            _force(cparams)
            tic3 = time.time()
            for i in range(steps):
                _, cparams, caux = cjit(cparams, cdata, caux,
                                        jax.random.fold_in(key, i))
            _force(cparams)
            cifar_rate = cbatch * steps / (time.time() - tic3)
        except Exception as e:
            sys.stderr.write("bench.py: cifar tier failed: %s\n" % e)

        # LSTM language-model tier (round-4 verdict #8): the reference's
        # RNN story is example/rnn/lstm_bucketing.py (PTB: 2x200 LSTM,
        # bptt 35, batch 32, vocab ~10k). Same protocol as the CIFAR
        # tier; metric is words/sec through the fused-scan sym.RNN.
        try:
            lstm_rate = _bench_lstm(compute_dtype, steps, on_accel, key,
                                    _force)
        except Exception as e:
            lstm_rate = None
            sys.stderr.write("bench.py: lstm tier failed: %s\n" % e)

        # trace artifact for the winner (round-3 evidence item): a
        # committed-on-round-end summary backs the MFU claims
        try:
            import tempfile

            import shutil

            tdir = tempfile.mkdtemp(prefix="bench_trace_")
            jax.profiler.start_trace(tdir)
            for i in range(5):
                outputs, params, aux = jit_step(
                    params, data, aux, jax.random.fold_in(key, 500 + i))
            _force(params)
            jax.profiler.stop_trace()
            here = os.path.dirname(os.path.abspath(__file__))
            sys.path.insert(0, os.path.join(here, "tools"))
            from trace_top import aggregate, find_trace_file, load_events

            rows, total_ms = aggregate(
                load_events(find_trace_file(tdir)), steps=5, by_op=False)
            with open(os.path.join(here, ".bench_trace_summary.json"),
                      "w") as f:
                json.dump({
                    "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                 time.gmtime()),
                    "chip": getattr(devices[0], "device_kind",
                                    devices[0].platform),
                    "layout": layout,
                    "batch": batch,
                    "device_ms_per_step": round(total_ms, 2),
                    "top_ops": [
                        {"ms_per_step": round(ms, 2),
                         "share_pct": round(share, 1),
                         "count": n, "op": name}
                        for ms, share, n, name in rows[:15]],
                }, f, indent=1)
            shutil.rmtree(tdir, ignore_errors=True)
        except Exception as e:
            sys.stderr.write("bench.py: trace summary failed: %s\n" % e)
    step_ms = elapsed / steps * 1000.0
    tflops_model = imgs_per_sec * RESNET50_TRAIN_GFLOPS_PER_IMG / 1e3 \
        if image == 224 else 0.0
    tflops_xla = xla_flops * steps / elapsed / 1e12
    peak = _chip_peak(getattr(devices[0], "device_kind", "")) \
        if on_accel else None
    result = {
        "metric": "resnet50_train_imgs_per_sec",
        "value": round(imgs_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
        "compute_dtype": dtype_name,
        "batch": batch,
        "layout": layout,
        "step_time_ms": round(step_ms, 2),
        "tflops_model": round(tflops_model, 1),
        "tflops_xla": round(tflops_xla, 1),
        "chip": getattr(devices[0], "device_kind", devices[0].platform),
    }
    if nhwc_rate is not None:
        result["imgs_per_sec_nhwc"] = round(nhwc_rate, 1)
    if cifar_rate is not None:
        # reference published 842 img/s (1x GTX 980, batch 128)
        result["cifar_inception_imgs_per_sec"] = round(cifar_rate, 1)
        result["vs_baseline_cifar"] = round(cifar_rate / 842.0, 3)
    if run_experiments and lstm_rate is not None:
        # the reference publishes no in-tree PTB words/sec; the absolute
        # rate stands on its own (lstm_bucketing.py geometry)
        result["lstm_ptb_words_per_sec"] = round(lstm_rate, 1)
    if peak and tflops_model:
        result["mfu_pct"] = round(100.0 * tflops_model / peak, 1)
    if peak and tflops_xla:
        result["mfu_pct_xla"] = round(100.0 * tflops_xla / peak, 1)

    # device observability plane: compile analytics + roofline + HBM
    # watermark. analytic_mfu is MFU from the executable's true FLOP
    # count (cost_analysis) and the measured step time — 0.0 where the
    # chip peak is unknown (CPU), with the FLOPs still recorded.
    hbm_wm.sample()
    xp = xprof.summary()
    xp["bench_analysis"] = xprof.analyze(
        xla_flops or None,
        bench_rec.bytes_accessed if bench_rec else None,
        step_time_s=elapsed / steps,
        device_kind=getattr(devices[0], "device_kind", "")
        if on_accel else None)
    result["compile_time_s"] = round(compile_time_s, 3) \
        if compile_time_s else 0.0
    result["analytic_mfu"] = \
        xp["bench_analysis"].get("analytic_mfu_pct") or 0.0
    result["peak_hbm_bytes"] = int(hbm_wm.peak)
    result["xprof"] = xp

    rec_env = _env.get("MXNET_TPU_BENCH_INPUT")
    if rec_env:
        result.update(_bench_recordio(jit_step, params, aux, key, batch,
                                      image, num_classes, steps, rec_env,
                                      _force, layout=layout))

    # fused-train-step probe: MXNET_TPU_FUSED_STEP rides the child's
    # inherited env, so `MXNET_TPU_FUSED_STEP=1 python bench.py` emits a
    # record self-labeled with the mode AND the measured dispatch count
    # behind it (expect ~1.0 fused vs 3+ classic)
    result["fused"] = _env.get("MXNET_TPU_FUSED_STEP")
    try:
        result["dispatches_per_step"] = _bench_fused_dispatch()
    except Exception as e:
        sys.stderr.write("bench.py: fused dispatch tier failed: %s\n" % e)

    # framework-side counters/spans for this run (engine, io, executor,
    # kvstore, bench.step span stats) ride along in the perf record
    result["telemetry"] = telemetry.snapshot()
    # ... and any anomaly events the step-trace detectors raised, so a
    # recompile-tainted or stall-tainted number is self-labeled
    events = list(tracing.step_trace().events)
    if events:
        result["anomaly_events"] = events

    # .bench_cache.json is deliberately git-TRACKED: the end-of-round
    # snapshot then preserves the last real on-chip measurement even
    # when the final bench run degrades to CPU (wedged tunnel)
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache.json")
    if on_accel:
        stamped = dict(result, measured_at=time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        try:
            with open(cache, "w") as f:
                json.dump(stamped, f)
        except OSError:
            pass
    else:
        # CPU fallback (accelerator absent or tunnel wedged): label it
        # and carry the last real on-chip measurement so the record
        # doesn't read as a throughput regression
        result["platform"] = "cpu-fallback"
        try:
            with open(cache) as f:
                result["last_accelerator_result"] = json.load(f)
        except (OSError, ValueError):
            pass
    print(json.dumps(result))


if __name__ == "__main__":
    main()
