"""Benchmark: ResNet-50 training throughput (images/sec) on one chip.

Prints ONE JSON line:
  {"metric": "resnet50_train_imgs_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": N}

Baseline: the reference publishes no in-tree ResNet-50 number
(BASELINE.md); the closest per-GPU proxy is ImageNet Inception-BN on
Titan X, batch 128: 1,281,167 img / 10,666 s ~= 120 img/s/GPU
(example/image-classification/README.md:245-253). vs_baseline =
ours / 120.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 120.0  # reference TitanX per-GPU Inception-BN proxy


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import build_sgd_train_step

    devices = jax.devices()
    on_accel = devices[0].platform != "cpu"
    batch = 256 if on_accel else 8
    image = 224 if on_accel else 32
    num_classes = 1000 if on_accel else 16
    steps = 10 if on_accel else 2

    net = models.get_resnet50(num_classes=num_classes,
                              small_input=not on_accel)
    shapes = {"data": (batch, 3, image, image)}
    arg_shapes, _, aux_shapes = net.infer_shape(**shapes)
    arg_names = net.list_arguments()
    rng = np.random.RandomState(0)

    params = {}
    data = {}
    for name, shape in zip(arg_names, arg_shapes):
        if name == "data":
            data[name] = jax.device_put(
                rng.rand(*shape).astype(np.float32), devices[0])
        elif name == "softmax_label":
            data[name] = jax.device_put(
                rng.randint(0, num_classes, shape).astype(np.float32),
                devices[0])
        elif name.endswith("gamma"):
            params[name] = jax.device_put(np.ones(shape, dtype=np.float32),
                                          devices[0])
        else:
            params[name] = jax.device_put(
                (rng.randn(*shape) * 0.05).astype(np.float32), devices[0])
    aux = [jax.device_put(np.ones(s, dtype=np.float32) if "var" in n
                          else np.zeros(s, dtype=np.float32), devices[0])
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)]

    # bf16 activations/matmuls with f32 master weights — the idiomatic
    # TPU precision (MXU native); override with MXNET_TPU_BENCH_DTYPE
    import os

    import jax.numpy as jnp
    dtype_name = os.environ.get("MXNET_TPU_BENCH_DTYPE",
                                "bfloat16" if on_accel else "float32")
    compute_dtype = None if dtype_name == "float32" \
        else getattr(jnp, dtype_name)
    step, _ = build_sgd_train_step(net, ["data"], ["softmax_label"],
                                   lr=0.01, compute_dtype=compute_dtype)
    # donate params/aux so XLA reuses their HBM buffers across steps
    jit_step = jax.jit(step, donate_argnums=(0, 2))
    key = jax.random.PRNGKey(0)

    def _force(tree):
        # fetch a scalar: block_until_ready alone can under-synchronize
        # through remote-device transports, inflating throughput
        leaf = next(iter(tree.values())) if isinstance(tree, dict) else tree
        return float(np.asarray(leaf.sum()))

    # warmup / compile (two steps: the donated-buffer steady state)
    outputs, params, aux = jit_step(params, data, aux, key)
    outputs, params, aux = jit_step(params, data, aux,
                                    jax.random.fold_in(key, steps + 1))
    _force(params)

    tic = time.time()
    for i in range(steps):
        outputs, params, aux = jit_step(params, data, aux,
                                        jax.random.fold_in(key, i))
    _force(params)
    elapsed = time.time() - tic

    imgs_per_sec = batch * steps / elapsed
    result = {
        "metric": "resnet50_train_imgs_per_sec",
        "value": round(imgs_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
        "compute_dtype": dtype_name,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
